#!/usr/bin/env python3
"""Utilization-plane CI gate (stage ``util-check``, ``make util``).

One tiny CPU engine, two generate rounds (warmup + steady state), then the
utilization attribution plane's standing invariants are asserted end to end:

1. per-program goodput fractions sum to 1 +- 1e-6 (the sum-to-capacity
   construction of obs/costmodel.py actually holds through the live engine)
2. padding efficiency lands in (0, 1] for every program that dispatched
3. the MFU/MBU families are exposed through /metrics on the null-peak path
   (CPU has no peak-table entry: TYPE headers present, no samples — and the
   achieved-FLOP/s / bytes/s gauges DO carry samples)
4. the recompile counter stays flat across the steady-state round: every
   compiled program was built in warmup, so a delta is a recompile storm
5. ledger totals and the scraped ``llmd_tpu:goodput_tokens_total`` counters
   agree exactly, and the bench-style measured-window delta accounting
   (bench.py's ``goodput_*`` provenance keys) reproduces the counter deltas
   token for token — the "bench JSON and live /metrics agree" contract

Run directly (CI) or via ``make util``. Exit 0 = all checks pass.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from llmd_tpu.core.request import SamplingParams  # noqa: E402
from llmd_tpu.engine.config import EngineConfig  # noqa: E402
from llmd_tpu.engine.engine import LLMEngine  # noqa: E402
from llmd_tpu.models.config import ModelConfig  # noqa: E402
from llmd_tpu.obs.costmodel import GOODPUT_KINDS  # noqa: E402


def _run(eng: LLMEngine, n: int, salt: int) -> None:
    for i in range(n):
        eng.add_request(f"u{salt}-{i}", list(range(1, 24 + i)),
                        SamplingParams(max_tokens=10, temperature=0.0))
    while eng.has_work():
        eng.step()


def _scrape_goodput(eng: LLMEngine) -> dict:
    """program -> kind -> value from the live registry counters."""
    out: dict = {}
    for name, labels, value in eng.metrics.registry.collect():
        if name != "llmd_tpu:goodput_tokens_total":
            continue
        prog = _label(labels, "program")
        kind = _label(labels, "kind")
        out.setdefault(prog, {})[kind] = value
    return out


def _label(rendered: str, key: str) -> str:
    # rendered labels look like {program="decode",kind="committed"}
    for part in rendered.strip("{}").split(","):
        k, _, v = part.partition("=")
        if k == key:
            return v.strip('"')
    raise AssertionError(f"label {key} not in {rendered}")


def main() -> int:
    t_start = time.monotonic()
    cfg = ModelConfig()
    eng = LLMEngine(cfg, EngineConfig(
        page_size=16, num_pages=96, max_model_len=256, max_batch_size=4,
        prefill_chunk=32, decode_steps=4, max_num_batched_tokens=64))
    assert eng.util is not None, (
        "LLMD_UTIL_LEDGER unexpectedly off — the gate must run with the "
        "ledger enabled")

    _run(eng, 3, salt=1)  # warmup: compiles every program this workload uses
    compiles_warm = eng.util.compiles()
    assert compiles_warm, "no program compiles recorded during warmup"
    base_totals = eng.util.totals()
    base_scrape = _scrape_goodput(eng)

    _run(eng, 4, salt=2)  # steady state: same shapes, zero fresh compiles

    # (1) fractions sum to 1 per program
    for prog in eng.util.programs():
        fr = eng.util.fractions(prog)
        s = sum(fr.values())
        assert abs(s - 1.0) <= 1e-6, (prog, fr, s)
        # (2) padding efficiency in (0, 1]
        pe = eng.util.padding_efficiency(prog)
        assert pe is not None and 0.0 < pe <= 1.0, (prog, pe)
    print(f"util-check: goodput fractions sum to 1 across "
          f"{len(eng.util.programs())} programs; padding efficiency in (0,1]")

    # (3) families exposed on the null-peak path
    expo = eng.metrics.registry.expose()
    for fam in ("llmd_tpu:program_mfu", "llmd_tpu:program_mbu"):
        assert f"# TYPE {fam} gauge" in expo, f"{fam} family not declared"
        assert not any(ln.startswith(fam + "{") for ln in expo.splitlines()), (
            f"{fam} exported samples on CPU — null peaks must mean no series")
    for fam in ("llmd_tpu:program_flops_per_second",
                "llmd_tpu:program_bytes_per_second"):
        assert any(ln.startswith(fam + "{") for ln in expo.splitlines()), (
            f"{fam} carried no samples")
    print("util-check: MFU/MBU families declared with null peaks; "
          "achieved-rate gauges carry samples")

    # (4) recompile counter flat across steady state
    compiles_now = eng.util.compiles()
    assert compiles_now == compiles_warm, (
        "recompiles during steady-state decode", compiles_warm, compiles_now)
    print(f"util-check: compile counts flat across steady state "
          f"({compiles_now})")

    # (5) ledger == /metrics, exactly; bench-style deltas reproduce them
    totals = eng.util.totals()
    scraped = _scrape_goodput(eng)
    for prog, tk in totals.items():
        for kind, v in tk.items():
            got = scraped.get(prog, {}).get(kind, 0.0)
            if v == 0 and kind not in scraped.get(prog, {}):
                continue  # zero classes never create counter children
            assert got == v, (prog, kind, v, got)
    bench_delta = {k: 0 for k in GOODPUT_KINDS}
    for prog, tk in totals.items():
        base = base_totals.get(prog, {})
        for kind, v in tk.items():
            bench_delta[kind] += v - base.get(kind, 0)
    scrape_delta = {k: 0.0 for k in GOODPUT_KINDS}
    for prog, tk in scraped.items():
        base = base_scrape.get(prog, {})
        for kind, v in tk.items():
            scrape_delta[kind] += v - base.get(kind, 0.0)
    assert {k: float(v) for k, v in bench_delta.items()} == scrape_delta, (
        bench_delta, scrape_delta)
    print(f"util-check: ledger == /metrics exactly; window deltas match "
          f"token for token ({bench_delta})")

    print(f"util-check: ALL OK ({time.monotonic() - t_start:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
