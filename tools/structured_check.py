#!/usr/bin/env python3
"""Structured-outputs gate: schema-constrained serving through the real stack.

Spins one in-process EngineServer (tiny model, CPU, byte tokenizer) and
drives N schema-constrained chat completions plus guided_choice/guided_regex
requests through the OpenAI surface. The gate holds when:

- every constrained response is 200 AND its content parses/validates against
  the constraint it was issued under (100% conformance, not a ratio),
- a malformed schema and a malformed logit_bias answer 400 (never 5xx),
- zero 5xx anywhere,
- all of the above holds again with the n-gram drafter live (spec_mode=
  "ngram"), i.e. the grammar-masked verify program keeps 100% conformance.

Run: python tools/structured_check.py  (CI: tools/ci_gate.py stage
`structured-check`, also `make structured`)
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_SCHEMA_REQUESTS = 8

SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string", "maxLength": 8},
        "count": {"enum": [0, 1, 2, 3]},
        "ok": {"type": "boolean"},
    },
    "required": ["name", "count", "ok"],
}
CHOICES = ["alpha", "beta", "gamma"]
REGEX = r"[a-c]{3}-[0-9]{2}"


# The whole battery runs twice: once plain, once with the n-gram drafter
# live so the grammar-masked verify program (PERF.md Lever 13) carries the
# constrained rows — conformance must be 100% either way, since greedy
# accept/reject keeps spec output bitwise identical to spec-off.
ENGINE_VARIANTS = [
    ("spec-off", {}),
    ("spec-ngram", {"spec_mode": "ngram", "spec_tokens": 4}),
]


async def drive_variant(label: str, spec_overrides: dict,
                        statuses: dict[int, int], bad: list[str]) -> None:
    import aiohttp

    from llmd_tpu.engine.config import EngineConfig
    from llmd_tpu.engine.server import EngineServer
    from llmd_tpu.models import get_model_config
    from llmd_tpu.structured import validate_instance

    server = EngineServer(
        get_model_config("tiny"),
        EngineConfig(page_size=8, num_pages=128, max_model_len=256,
                     max_batch_size=4, prefill_chunk=32, **spec_overrides),
        model_name="llmd-tpu/tiny", port=0)
    await server.start()
    try:
        async with aiohttp.ClientSession() as sess:
            async def chat(body: dict) -> tuple[int, str]:
                body = {"model": "llmd-tpu/tiny", "max_tokens": 64,
                        "temperature": 0.0, **body}
                async with sess.post(
                    f"http://{server.address}/v1/chat/completions", json=body,
                    timeout=aiohttp.ClientTimeout(total=120),
                ) as r:
                    statuses[r.status] = statuses.get(r.status, 0) + 1
                    if r.status != 200:
                        return r.status, await r.text()
                    data = await r.json()
                    return 200, data["choices"][0]["message"]["content"]

            # N schema-constrained requests (varied prompts; temperature 0.7
            # on half so the sampled path is exercised too)
            for i in range(N_SCHEMA_REQUESTS):
                status, text = await chat({
                    "messages": [{"role": "user",
                                  "content": f"emit record {i} " * (i + 1)}],
                    "temperature": 0.7 if i % 2 else 0.0,
                    "seed": i,
                    "response_format": {"type": "json_schema",
                                        "json_schema": {"schema": SCHEMA}},
                })
                if status != 200:
                    bad.append(f"{label}/schema[{i}]: HTTP {status}: "
                               f"{text[:200]}")
                    continue
                try:
                    value = json.loads(text)
                except ValueError:
                    bad.append(f"{label}/schema[{i}]: not JSON: {text!r}")
                    continue
                if not validate_instance(value, SCHEMA):
                    bad.append(f"{label}/schema[{i}]: fails schema: {value!r}")

            status, text = await chat({
                "messages": [{"role": "user", "content": "pick one"}],
                "guided_choice": CHOICES,
            })
            if status != 200 or text not in CHOICES:
                bad.append(f"{label}/choice: HTTP {status}: {text!r}")
            status, text = await chat({
                "messages": [{"role": "user", "content": "match it"}],
                "guided_regex": REGEX,
            })
            if status != 200 or not re.fullmatch(REGEX, text):
                bad.append(f"{label}/regex: HTTP {status}: {text!r}")

            # malformed inputs must answer 400 (and never reach the engine);
            # admission rejects these before the engine config matters, so
            # one pass on the plain variant covers the contract
            if spec_overrides:
                return
            for case, body in (
                ("bad-schema", {"messages": [{"role": "user", "content": "x"}],
                                "response_format": {
                                    "type": "json_schema",
                                    "json_schema": {"schema": {
                                        "type": "object",
                                        "properties": {"x": {"type": "wat"}},
                                        "required": ["x"]}}}}),
                ("bad-rf-type", {"messages": [{"role": "user", "content": "x"}],
                                 "response_format": {"type": "yaml_object"}}),
                ("bad-logit-bias", {"messages": [{"role": "user",
                                                  "content": "x"}],
                                    "logit_bias": {"7": 9000}}),
            ):
                status, text = await chat(body)
                if status != 400:
                    bad.append(f"{label}/{case}: expected 400, got {status}: "
                               f"{text[:200]}")
    finally:
        await server.stop()


async def main_async() -> int:
    statuses: dict[int, int] = {}
    bad: list[str] = []
    t0 = time.monotonic()
    for label, spec_overrides in ENGINE_VARIANTS:
        await drive_variant(label, spec_overrides, statuses, bad)

    wall = time.monotonic() - t0
    n_5xx = sum(n for code, n in statuses.items() if code >= 500)
    verdict = not bad and n_5xx == 0
    print(json.dumps({
        "structured_check": "ok" if verdict else "failed",
        "engine_variants": [label for label, _ in ENGINE_VARIANTS],
        "schema_requests": N_SCHEMA_REQUESTS * len(ENGINE_VARIANTS),
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "failures": bad,
        "wall_s": round(wall, 2),
    }, indent=2))
    if not verdict:
        print(f"structured_check: FAILED — {len(bad)} failures, "
              f"{n_5xx} 5xx", file=sys.stderr)
        return 1
    return 0


def main() -> int:
    return asyncio.run(main_async())


if __name__ == "__main__":
    sys.exit(main())
