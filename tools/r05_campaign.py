"""One-command on-chip perf campaign (r05): baseline vs int8 vs batch sweep
vs long-context, each a fresh bench.py subprocess, all artifacts in one JSON.

The fabric has been intermittent; this script is built to harvest whatever
window it gets: every point is independent (a failure or a device drop mid-
campaign keeps every completed point), bench.py's own preflight turns a dead
fabric into a structured skip rather than a crash, and partial results are
flushed to disk after every point. After any point times out, a cheap
subprocess probe checks whether the fabric is still alive; if it is dead the
remaining points are recorded as structured skips immediately instead of each
paying bench.py's full 180s preflight (the r05 b128 run burned ~30 min
discovering a fabric that died mid-point, one preflight at a time).

Every bench subprocess shares one attention tune table
(campaign_logs/attn_tune.json via LLMD_ATTN_TUNE_FILE): bench.py's on-chip
tuner merges each point's winning block sizes into it, so later points (and
re-runs after a fabric drop) start from the accumulated table, and each
result row carries the loaded table's hash (attn_tune_hash) as provenance.

Usage: python tools/r05_campaign.py [--out BENCH_CAMPAIGN_r05.json]
                                    [--skip baseline-bf16,int8,...]
A re-run merges into an existing --out file: completed points are kept unless
named for re-running (i.e. not skipped), so a fabric drop mid-campaign costs
only the missed points.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Ordered by value-per-device-minute: windows close without warning, so the
# headline configs re-measure first (the horizon-clamp dispatch fix makes all
# pre-fix rows stale) and exploratory points run last. An optional third
# element overrides the program run for the point (default bench.py) — the
# warm-start point runs tools/warm_start_probe.py, which speaks the same
# one-JSON-line contract.
POINTS: list[tuple] = [
    # serving default re-measure with pipelined prefill sampling (engine
    # default since the 2nd window): A/B against the harvested int8-b64 row
    # (4,042 tok/s), which pre-dates the deferred sample read
    ("int8-b64-pps", ["--quantize", "int8", "--batch", "64"]),
    # b128's first attempt hit the 1500s ceiling — the fabric died mid-point
    # (the very next point found it dead; CPU probes show per-step cost scales
    # linearly b64->b128, no code pathology — see PERF.md round 6 and
    # tests/test_paged_attention.py's bounded-cost regression). Retry early;
    # per-point stderr logs survive a timeout and the post-timeout fabric
    # probe above turns a repeat death into fast skips instead of 30 min of
    # serial preflights
    ("int8-b128", ["--quantize", "int8", "--batch", "128"]),
    # token-sorted MoE dispatch A/B (PERF.md Lever 14) on the MoE-wide MLA
    # registry shape: sorted drop-free gather/scatter dispatch vs the legacy
    # capacity einsum at matched routing decisions. The pair's decode tok/s
    # delta is the lever's on-chip number; drop + comm-byte provenance rides
    # the JSON row (moe_dropped_tokens / moe_comm_bytes). Not best_serving-
    # eligible (different model), like the mla-decode pair.
    ("int8-b64-moe-sorted", ["--model", "moe-wide-mla", "--quantize", "int8",
                             "--batch", "64", "--moe-dispatch", "sorted"]),
    ("int8-b64-moe-einsum", ["--model", "moe-wide-mla", "--quantize", "int8",
                             "--batch", "64", "--moe-dispatch", "einsum"]),
    # layer-scan unroll A/B at the serving default: can XLA hide part of the
    # weight stream behind compute across layer boundaries?
    # speculative decoding A/B vs the harvested int8-b64 row (4,042 tok/s):
    # uniform workload bounds the overhead when drafts rarely match; the echo
    # point measures the upside in the regime prompt-lookup targets (shared-
    # prefix/agentic/summarization traffic whose outputs repeat the context),
    # with acceptance-rate provenance in the JSON row
    ("int8-b64-spec", ["--quantize", "int8", "--batch", "64",
                       "--spec-mode", "ngram"]),
    ("int8-b64-spec-echo", ["--quantize", "int8", "--batch", "64",
                            "--spec-mode", "ngram", "--workload", "echo"]),
    # structured-outputs A/B vs the int8-b64 row: every request schema-
    # constrained (response_format json_schema). Since Lever 12, constrained
    # rows ride the fused masked decode program (device-resident bias + FSM),
    # so this point prices per-chain table staging + the masked chain; the
    # -fused-off twin re-measures the legacy 1-token unified degrade for the
    # A/B. Like the spec echo row, excluded from best_serving (different
    # workload).
    ("int8-b64-structured", ["--quantize", "int8", "--batch", "64",
                             "--workload", "json"]),
    ("int8-b64-structured-fused-off",
     ["--quantize", "int8", "--batch", "64", "--workload", "json",
      "--structured-fused", "off"]),
    # structured x speculative compose A/B (PERF.md Lever 13): constrained-
    # echo workload (fully-forced periodic array serialization) with the
    # grammar-masked verify program drafting through the constraint, vs the
    # same workload on the plain fused masked chain. The pair's delta is the
    # lever's on-chip number; acceptance provenance rides the JSON row
    # (spec_drafted_constrained / spec_accepted_constrained). Excluded from
    # best_serving (different workload), like the other echo/json rows.
    ("int8-b64-spec-json", ["--quantize", "int8", "--batch", "64",
                            "--spec-mode", "ngram", "--workload", "json-echo"]),
    ("int8-b64-spec-json-off", ["--quantize", "int8", "--batch", "64",
                                "--workload", "json-echo"]),
    # Lever 12 pack-overlap A/B at the serving default: off restores the
    # serialized full pack (and its time_host_pack accounting), so the pair's
    # serialized_host_s delta is the lever's measured host-time win on-chip
    ("int8-b64-packoff", ["--quantize", "int8", "--batch", "64",
                          "--pack-overlap", "off"]),
    # MLA latent-decode kernel A/B on the MoE-wide MLA registry shape
    # (ops/mla_decode Pallas vs the absorbed XLA reference) — not
    # best_serving-eligible (different model)
    ("mla-decode-pallas", ["--model", "moe-wide-mla", "--quantize", "none",
                           "--batch", "32", "--attn-impl", "pallas"]),
    ("mla-decode-xla", ["--model", "moe-wide-mla", "--quantize", "none",
                        "--batch", "32", "--attn-impl", "reference"]),
    # real-replica warm start: cold vs warm relaunch against one persistent
    # compilation cache (the pool controller's warm-start path), measured on
    # the actual device. Prog override — runs the probe, not bench.py.
    ("warm-start-replica", ["--model", "llama-1b"],
     "tools/warm_start_probe.py"),
    ("int8-b64-unroll4", ["--quantize", "int8", "--batch", "64",
                          "--layer-unroll", "4"]),
    ("int8-b64-unroll16", ["--quantize", "int8", "--batch", "64",
                           "--layer-unroll", "16"]),
    ("baseline-bf16", ["--quantize", "none", "--batch", "32"]),  # r04 shape: NT=8192, k=32, b=32
    ("int8", ["--quantize", "int8", "--batch", "32"]),
    ("b64-bf16", ["--quantize", "none", "--batch", "64"]),
    ("b128-bf16", ["--quantize", "none", "--batch", "128"]),
    ("longctx-isl2048", ["--isl", "2048", "--osl", "128", "--batch", "16",
                         "--quantize", "none"]),
    ("longctx-int8", ["--isl", "2048", "--osl", "128", "--batch", "16",
                      "--quantize", "int8"]),
    # fp8-KV points were DROPPED after the 2nd window measured the pool at
    # int8-b64 as a 32% regression (2,732 vs 4,042 tok/s): v5e has no native
    # fp8 datapath, so the in-kernel dequant outweighs the halved page DMA.
    # The harvested int8-b64-kvfp8 row stays in the artifact as the evidence;
    # the flag remains for fp8-native TPUs (v7x).
]


ATTN_TUNE_FILE = os.path.join(ROOT, "campaign_logs/attn_tune.json")


def fabric_alive(timeout_s: float = 90.0) -> bool:
    """Probe the TPU fabric in a throwaway subprocess — the shared probe from
    llmd_tpu.obs.device, so the bench harness and the serving DeviceMonitor
    agree on what "fabric dead" means. Used after a point times out to decide
    between 'keep going' and 'fast-skip the rest with structured rows'.
    """
    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    from llmd_tpu.obs.device import fabric_alive_subprocess

    return fabric_alive_subprocess(timeout_s=timeout_s, platform="tpu",
                                   cwd=ROOT)


def run_point(name: str, extra: list[str], timeout_s: float,
              prog: str = "bench.py") -> dict:
    cmd = [sys.executable, os.path.join(ROOT, prog)] + extra
    print(f"=== {name}: {' '.join(cmd)}", flush=True)
    t0 = time.monotonic()
    # stream stderr (bench.py's phase trace) to a per-point log so a
    # timeout/fabric drop still leaves the trace behind (the b128 1500s
    # timeout taught us this: capture_output keeps it in a pipe the kill
    # throws away); stdout stays piped — it only carries the result JSON
    log_path = os.path.join(ROOT, f"campaign_logs/{name}.log")
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    # every point reads AND extends the same attention tune table — later
    # points inherit earlier points' block-size winners, and the engine
    # stamps the table hash into the row (attn_tune_hash)
    env = {**os.environ, "LLMD_ATTN_TUNE_FILE": ATTN_TUNE_FILE}
    try:
        with open(log_path, "w") as log:
            p = subprocess.run(cmd, cwd=ROOT, stdout=subprocess.PIPE,
                               stderr=log, text=True, timeout=timeout_s,
                               env=env)
    except subprocess.TimeoutExpired:
        return {"point": name, "error": f"timeout {timeout_s:.0f}s",
                "log": log_path}
    with open(log_path) as f:
        log_tail = f.read()
    sys.stderr.write(log_tail[-1500:] + "\n")
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
            if out.get("source"):
                # bench.py's device-unavailable path can re-emit a HARVESTED
                # row (flag-default invocation only, but belt-and-braces):
                # relabeling it to this point would fabricate a measurement
                return {"point": name, "error": "device-unavailable",
                        "note": "bench returned harvested fallback, discarded"}
            out["point"] = name
            out["wall_total_s"] = round(time.monotonic() - t0, 1)
            return out
        except json.JSONDecodeError:
            continue
    return {"point": name, "error": f"no JSON (rc={p.returncode})",
            "tail": (log_tail or p.stdout)[-400:], "log": log_path}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_CAMPAIGN_r05.json")
    ap.add_argument("--skip", default="",
                    help="comma-separated point names to skip")
    ap.add_argument("--timeout", type=float, default=1500.0)
    args = ap.parse_args()
    skip = set(filter(None, args.skip.split(",")))
    known = {p[0] for p in POINTS}
    for s in skip - known:
        print(f"# WARNING: --skip name {s!r} matches no point "
              f"(known: {', '.join(sorted(known))})", file=sys.stderr)
    out_path = os.path.join(ROOT, args.out)

    # a re-run (e.g. --skip of already-harvested points after a fabric drop)
    # must MERGE with the existing artifact, not erase the harvested points.
    # A prior entry survives until its replacement actually completes — a
    # second fabric drop mid-re-run must not cost points it never re-reached.
    prior: list[dict] = []
    if os.path.exists(out_path):
        try:
            prior = json.load(open(out_path)).get("results", [])
        except (json.JSONDecodeError, OSError):
            prior = []
        if prior:
            print(f"# merging into {len(prior)} prior point(s) from {args.out}",
                  file=sys.stderr)

    points = [p for p in POINTS if p[0] not in skip]
    if not points:
        print(json.dumps({"error": "every point skipped"}))
        return
    results: list[dict] = []
    dead_after: "str | None" = None  # point whose timeout found the fabric dead
    for entry in points:
        name, extra = entry[0], entry[1]
        prog = entry[2] if len(entry) > 2 else "bench.py"
        if dead_after is not None:
            # fabric confirmed dead: structured skip, same shape as bench.py's
            # own preflight skip rows, but issued here in ~0s instead of after
            # another 2x180s in-subprocess preflight per point
            results.append({"point": name, "error": "skipped",
                            "note": f"fabric dead (probe failed after "
                                    f"{dead_after!r} timed out)"})
        else:
            row = run_point(name, extra, args.timeout, prog)
            results.append(row)
            if str(row.get("error", "")).startswith("timeout"):
                # a timeout is ambiguous: slow point vs fabric death mid-point
                # (the r05 b128 row was the latter). Disambiguate cheaply.
                print(f"# {name} timed out; probing fabric...", file=sys.stderr)
                if not fabric_alive():
                    dead_after = name
                    print("# fabric probe failed: fast-skipping remaining "
                          "points", file=sys.stderr)
        prior_good = {r["point"] for r in prior if r.get("value")}
        # a completed re-run supersedes its prior entry; a FAILED re-run must
        # not replace a prior real measurement with an error row
        keep_new = [r for r in results
                    if r.get("value") or r.get("point") not in prior_good]
        done = {r.get("point") for r in keep_new}
        merged = [r for r in prior if r.get("point") not in done] + keep_new
        serving = [r for r in merged
                   if r.get("value")
                   and not r["point"].startswith(("longctx", "mla-", "warm-"))
                   and "-moe-" not in r["point"]
                   and r.get("metric") == "output_tok_per_s_per_chip"
                   and r.get("workload", "uniform") == "uniform"]
        best = max(serving, key=lambda r: r["value"]) if serving else None
        with open(out_path, "w") as f:  # flush after EVERY point
            json.dump({
                "campaign": "r05",
                "reference_r03": {"value": 1930.0, "weights_bw_util": 0.153},
                # shared tune table: each result row's attn_tune_hash tells
                # which snapshot of this file the point actually served with
                "attn_tune_file": os.path.relpath(ATTN_TUNE_FILE, ROOT),
                "results": merged,
                "best_serving": ({"point": best["point"], "value": best["value"],
                                  "weights_bw_util": best.get("weights_bw_util")}
                                 if best else None),
            }, f, indent=2)
    print(json.dumps(json.load(open(out_path))["best_serving"] or {}))


if __name__ == "__main__":
    main()
