"""Real-replica warm-start probe: cold vs warm engine relaunch against one
persistent JAX compilation cache.

The pool controller's warm-start path (pool/controller.py, `pool_warm_start`
flight event) points a replica relaunch at a snapshot's compilation cache so
the engine's jitted programs deserialize instead of re-tracing. This probe
measures what that actually buys on a real replica: it launches the SAME
engine build twice in throwaway subprocesses sharing one
``jax_compilation_cache_dir`` — the first (cold) populates the cache, the
second (warm) is the relaunch the controller performs — and reports
ready-time (engine build + first compile-dominated generate) for both.

Prints ONE campaign-compatible JSON line:
``{"metric": "warm_start_speedup", "value": <cold_ready/warm_ready>, ...}``
with the full cold/warm phase rows as provenance. Child failures emit a
structured skip (rc=0), matching bench.py's device-unavailable contract so
tools/r05_campaign.py can queue this as a device-window point.

Usage: python tools/warm_start_probe.py [--model tiny] [--cpu]
                                        [--cache-dir DIR] [--keep-cache]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time


def _child(args: argparse.Namespace) -> None:
    """One replica launch: build the engine, run the first generate, report
    phase walls. Runs in its own process so the in-memory jit cache of a
    prior launch can never masquerade as the persistent cache's win."""
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", args.cache_dir)
    # cache every program regardless of compile time/entry size — the tiny
    # smoke's programs compile in ms and would otherwise never persist
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # knob names drift across JAX versions; cache still works

    from llmd_tpu.core.request import SamplingParams
    from llmd_tpu.engine import EngineConfig, LLMEngine
    from llmd_tpu.models import resolve_model

    t0 = time.monotonic()
    cfg, params = resolve_model(args.model)
    load_s = time.monotonic() - t0
    ecfg = EngineConfig(page_size=16, num_pages=256, max_model_len=512,
                        max_batch_size=4, prefill_chunk=64, decode_steps=8)
    t0 = time.monotonic()
    eng = LLMEngine(cfg, ecfg, params=params)
    build_s = time.monotonic() - t0
    prompts = [[(i * 131 + j) % (cfg.vocab_size - 2) + 1 for j in range(32)]
               for i in range(2)]
    t0 = time.monotonic()
    out = eng.generate(prompts, SamplingParams(max_tokens=16, temperature=0.0,
                                               ignore_eos=True))
    first_generate_s = time.monotonic() - t0  # compile-dominated when cold
    assert sum(len(v) for v in out.values()) == 2 * 16
    print(json.dumps({
        "load_s": round(load_s, 3),
        "build_s": round(build_s, 3),
        "first_generate_s": round(first_generate_s, 3),
        # the number the controller's relaunch budget cares about: engine up
        # AND serving its first tokens (weight load excluded — a snapshot
        # restore prices that separately)
        "ready_s": round(build_s + first_generate_s, 3),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny",
                    help="registry name or HF checkpoint dir (the replica "
                         "being relaunched)")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU platform (CI smoke; the cache round trip "
                         "is the same code, the speedup is only meaningful "
                         "on-device)")
    ap.add_argument("--cache-dir", default=None,
                    help="shared jax_compilation_cache_dir (default: a "
                         "campaign_logs/warm_cache dir next to the repo root)")
    ap.add_argument("--keep-cache", action="store_true",
                    help="reuse an existing cache instead of wiping it first "
                         "(wiping is what makes the cold launch cold)")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-launch subprocess budget in seconds")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args.cache_dir = os.path.abspath(
        args.cache_dir or os.path.join(root, "campaign_logs", "warm_cache"))
    if args.child:
        _child(args)
        return

    if not args.keep_cache and os.path.isdir(args.cache_dir):
        shutil.rmtree(args.cache_dir)
    os.makedirs(args.cache_dir, exist_ok=True)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--model", args.model, "--cache-dir", args.cache_dir]
    if args.cpu:
        cmd.append("--cpu")
    rows: dict[str, dict] = {}
    for label in ("cold", "warm"):
        t0 = time.monotonic()
        env = {**os.environ,
               "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", "")}
        try:
            p = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                               env=env, timeout=args.timeout)
        except subprocess.TimeoutExpired:
            print(json.dumps({"metric": "warm_start_speedup", "value": None,
                              "unit": "x", "vs_baseline": None,
                              "skipped": f"{label}-launch-timeout"}))
            return
        if p.returncode != 0:
            # same rc=0 structured-skip contract as bench.py's preflight: a
            # flaky fabric must not erase the campaign point as a crash
            tail = (p.stderr or p.stdout or "").strip().splitlines()
            print(json.dumps({"metric": "warm_start_speedup", "value": None,
                              "unit": "x", "vs_baseline": None,
                              "skipped": f"{label}-launch-failed",
                              "error": (tail[-1] if tail else "")[:500]}))
            return
        row = json.loads(p.stdout.strip().splitlines()[-1])
        row["wall_s"] = round(time.monotonic() - t0, 3)
        rows[label] = row
        print(f"# {label} launch: ready {row['ready_s']:.2f}s "
              f"(build {row['build_s']:.2f}s + first-generate "
              f"{row['first_generate_s']:.2f}s)", file=sys.stderr)
    entries = sum(len(fs) for _, _, fs in os.walk(args.cache_dir))
    cold, warm = rows["cold"], rows["warm"]
    print(json.dumps({
        "metric": "warm_start_speedup",
        "value": round(cold["ready_s"] / max(1e-9, warm["ready_s"]), 2),
        "unit": "x",
        "vs_baseline": None,
        "model": args.model,
        "cold": cold,
        "warm": warm,
        "cold_ready_s": cold["ready_s"],
        "warm_ready_s": warm["ready_s"],
        "cache_entries": entries,
        "cache_dir": args.cache_dir,
        "platform": "cpu" if args.cpu else "device",
    }))


if __name__ == "__main__":
    main()
