#!/usr/bin/env python3
"""Metrics doc-contract linter (CI stage lint-metrics) — shim over
tools/llmd_lint/metrics_contract.py.

The observability kit (grafana dashboards, alert rules, the promql cookbook)
must only reference metric families the stack actually emits: the shared
registry's declared families (expanded with histogram/summary series
suffixes) plus raw-line providers found by scanning the source. The checked
contract and output format are unchanged from the pre-framework linter; the
same analyzer also runs in the ``llmd-lint`` stage.

Run directly (CI) or via tests/test_lint.py. Exit 0 = contract holds.
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.llmd_lint import metrics_contract as _mc  # noqa: E402
from tools.llmd_lint.metrics_contract import METRIC_PAT  # noqa: E402,F401


def registry_families() -> set[str]:
    return _mc.registry_families(ROOT)


def rawline_families() -> set[str]:
    return _mc.rawline_families(ROOT)


def referenced() -> dict[str, list[str]]:
    return _mc.referenced(ROOT)


def lint() -> list[str]:
    emitted = registry_families() | rawline_families()
    return [f.message for f in _mc.evaluate(emitted, referenced())]


def main() -> int:
    errors = lint()
    for e in errors:
        print(f"METRICS-LINT: {e}")
    print(f"metrics contract: "
          f"{'OK' if not errors else f'{len(errors)} dangling reference(s)'}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
