#!/usr/bin/env python3
"""Metrics contract linter: the observability kit must only reference metric
families the stack actually emits.

Sources of truth, in order:

1. the registry — `llmd_tpu.obs.metrics.register_*` declare every family the
   engine, engine frontends, and router expose through `Registry.expose()`;
   histograms/summaries also emit their `_bucket`/`_sum`/`_count` series;
2. raw-line providers — plugins that append pre-rendered exposition lines
   (latency predictor, ext-proc front, HA coordinator, predictor sidecar) are
   found by scanning the source for family-shaped names.

Checked consumers: `observability/grafana/*.json` panel targets,
`observability/alerts.yaml` rule expressions, and the `observability/promql.md`
cookbook. Any referenced family not emitted anywhere is a dangling reference.

Run directly (CI via tools/ci_gate.py) or through tests. Exit 0 = no dangling
references.
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

# family-shaped names used across the stack (same pattern test_lint.py uses)
METRIC_PAT = re.compile(
    r"(llmd_tpu:[a-z_]+|llm_d_epp_[a-z_]+|igw_[a-z_]+|vllm:[a-z_]+"
    r"|inference_objective_[a-z_]+)")


def registry_families() -> set[str]:
    """Every family name the shared registry declares, expanded with the
    series suffixes histograms and summaries emit."""
    sys.path.insert(0, str(ROOT))
    try:
        from llmd_tpu.obs.metrics import (
            Histogram,
            Registry,
            Summary,
            register_engine_metrics,
            register_engine_server_metrics,
            register_pool_metrics,
            register_router_metrics,
        )
    finally:
        sys.path.remove(str(ROOT))

    reg = Registry()
    register_engine_metrics(reg)
    register_engine_server_metrics(reg)
    register_router_metrics(reg)
    register_pool_metrics(reg)
    names: set[str] = set()
    for name in reg.families():
        names.add(name)
        fam = reg.get(name)
        if isinstance(fam, Histogram):
            names |= {name + "_bucket", name + "_sum", name + "_count"}
        elif isinstance(fam, Summary):
            names |= {name + "_sum", name + "_count"}
    return names


def rawline_families() -> set[str]:
    """Family names emitted as pre-rendered lines (plugin providers, sidecars)
    anywhere in the source tree."""
    names: set[str] = set()
    for py in (ROOT / "llmd_tpu").rglob("*.py"):
        names |= set(METRIC_PAT.findall(py.read_text(errors="replace")))
    return names


def referenced() -> dict[str, list[str]]:
    """Metric names referenced by the observability kit → referencing files."""
    refs: dict[str, list[str]] = {}

    def note(name: str, where: str) -> None:
        refs.setdefault(name, []).append(where)

    for dash in sorted((ROOT / "observability" / "grafana").glob("*.json")):
        doc = json.loads(dash.read_text())
        for panel in doc.get("panels", []):
            for tgt in panel.get("targets", []):
                for m in METRIC_PAT.findall(tgt.get("expr", "")):
                    note(m, f"grafana/{dash.name}")
    alerts = ROOT / "observability" / "alerts.yaml"
    for m in METRIC_PAT.findall(alerts.read_text()):
        note(m, "alerts.yaml")
    promql = ROOT / "observability" / "promql.md"
    for m in METRIC_PAT.findall(promql.read_text()):
        note(m, "promql.md")
    return refs


def lint() -> list[str]:
    emitted = registry_families() | rawline_families()
    errors: list[str] = []
    for name, where in sorted(referenced().items()):
        if name not in emitted:
            errors.append(
                f"{name}: referenced by {sorted(set(where))} but no registry "
                f"family or raw-line provider emits it")
    return errors


def main() -> int:
    errors = lint()
    for e in errors:
        print(f"METRICS-LINT: {e}")
    print(f"metrics contract: "
          f"{'OK' if not errors else f'{len(errors)} dangling reference(s)'}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
