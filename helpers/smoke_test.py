#!/usr/bin/env python3
"""Deployment smoke test: verify a serving stack is healthy end to end (A7).

The TPU-stack analogue of the reference's healthcheck helper
(/root/reference/helpers/smoke-test/README.md): liveness, readiness with model
auto-discovery, and a real inference round trip with a latency bound — exit
code 0/1 for CI gates, ``-o json`` for machine consumption. Pure stdlib, so it
runs in any pod or laptop with Python (no curl/jq dependencies).

Usage:
  python helpers/smoke_test.py                         # localhost:8000
  python helpers/smoke_test.py -e http://gw:80 -m m    # explicit endpoint/model
  python helpers/smoke_test.py --api chat -l 5000      # chat path, 5s budget
  python helpers/smoke_test.py -o json                 # CI output
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def _get(url: str, timeout: float):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read() or b"{}")


def _post(url: str, body: dict, timeout: float):
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def run_checks(endpoint: str, model: str | None, api: str, latency_ms: float,
               require_health: bool, timeout: float, max_tokens: int = 8) -> dict:
    results: dict = {"endpoint": endpoint, "checks": [], "ok": True}

    def record(name: str, ok: bool, detail: str, ms: float | None = None):
        results["checks"].append(
            {"name": name, "ok": ok, "detail": detail, "latency_ms": ms})
        if not ok:
            results["ok"] = False

    # liveness (optional — many gateways don't expose /health)
    t0 = time.monotonic()
    try:
        status, _ = _get(f"{endpoint}/health", timeout)
        record("health", status == 200, f"HTTP {status}",
               (time.monotonic() - t0) * 1e3)
    except Exception as e:
        record("health", not require_health, f"unreachable: {e}")

    # readiness + model discovery
    t0 = time.monotonic()
    try:
        status, body = _get(f"{endpoint}/v1/models", timeout)
        ids = [m.get("id") for m in body.get("data", [])]
        ok = status == 200 and bool(ids)
        record("models", ok, f"HTTP {status}, models={ids}",
               (time.monotonic() - t0) * 1e3)
        if model is None and ids:
            model = ids[0]
    except Exception as e:
        record("models", False, f"unreachable: {e}")
    if model is None:
        record("inference", False, "no model discovered and none given (-m)")
        return results

    # end-to-end inference (with cross-API fallback, like the reference):
    # ONE working API suffices in auto mode — earlier attempts' failures only
    # count when every API fails
    apis = [api] if api != "auto" else ["completions", "chat"]
    attempts: list[tuple[str, bool, str, float | None]] = []
    for which in apis:
        path = "/v1/chat/completions" if which == "chat" else "/v1/completions"
        body = ({"model": model, "max_tokens": max_tokens, "temperature": 0.0,
                 "messages": [{"role": "user", "content": "ping"}]}
                if which == "chat" else
                {"model": model, "max_tokens": max_tokens, "temperature": 0.0,
                 "prompt": "ping"})
        t0 = time.monotonic()
        try:
            status, resp = _post(f"{endpoint}{path}", body, timeout)
            ms = (time.monotonic() - t0) * 1e3
            choice = (resp.get("choices") or [{}])[0]
            text = (choice.get("message") or {}).get("content") if which == "chat" \
                else choice.get("text")
            ok = status == 200 and text is not None
            if ok and latency_ms and ms > latency_ms:
                attempts.append((which, False,
                                 f"latency {ms:.0f}ms > budget {latency_ms:.0f}ms", ms))
            else:
                attempts.append((which, ok, f"HTTP {status}", ms))
        except Exception as e:
            attempts.append((which, False, f"error: {e}", None))
        if attempts[-1][1]:
            record(f"inference:{which}", True, attempts[-1][2], attempts[-1][3])
            return results
    for which, ok, detail, ms in attempts:
        record(f"inference:{which}", ok, detail, ms)
    return results


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-e", "--endpoint", default="http://localhost:8000")
    ap.add_argument("-m", "--model", default=None)
    ap.add_argument("--api", choices=["auto", "completions", "chat"], default="auto")
    ap.add_argument("-l", "--latency-ms", type=float, default=0.0,
                    help="fail if inference exceeds this (0 = no bound)")
    ap.add_argument("--require-health", action="store_true")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("-o", "--output", choices=["text", "json"], default="text")
    args = ap.parse_args()

    results = run_checks(args.endpoint.rstrip("/"), args.model, args.api,
                         args.latency_ms, args.require_health, args.timeout)
    if args.output == "json":
        print(json.dumps(results))
    else:
        for c in results["checks"]:
            mark = "PASS" if c["ok"] else "FAIL"
            lat = f" ({c['latency_ms']:.0f} ms)" if c.get("latency_ms") else ""
            print(f"[{mark}] {c['name']}: {c['detail']}{lat}")
        print("smoke test:", "OK" if results["ok"] else "FAILED")
    return 0 if results["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
