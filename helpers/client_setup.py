#!/usr/bin/env python3
"""Client-setup checker (A7): verify the tools a workstation needs to operate
the stack, before any guide is attempted.

The reference ships an install script for its CLI toolchain
(/root/reference/helpers/client-setup/README.md); in this stack the client
surface is Python, so the helper VERIFIES the environment (imports, native
toolchain, optional extras) and prints exactly what is missing and why it
matters. --strict exits non-zero for CI images.
"""

from __future__ import annotations

import argparse
import importlib.util
import shutil
import sys

REQUIRED = [
    ("jax", "engine + sharding dry-runs"),
    ("numpy", "everything"),
    ("aiohttp", "engine/router/sidecar HTTP servers"),
    ("yaml", "router plugin-graph configs, manifests"),
]
OPTIONAL = [
    ("zmq", "precise prefix routing (KV event subscription)"),
    ("sklearn", "latency predictor (GBDT)"),
    ("transformers", "HF tokenizer + checkpoint loading"),
    ("grpc", "gateway mode (Envoy ext_proc)"),
]
TOOLS = [
    ("g++", "native KV-transfer data plane build"),
    ("kubectl", "applying deploy/ manifests (cluster use only)"),
]


def check() -> dict:
    out = {"required": [], "optional": [], "tools": [], "ok": True}
    for mod, why in REQUIRED:
        ok = importlib.util.find_spec(mod) is not None
        out["required"].append((mod, ok, why))
        out["ok"] = out["ok"] and ok
    for mod, why in OPTIONAL:
        out["optional"].append((mod, importlib.util.find_spec(mod) is not None, why))
    for tool, why in TOOLS:
        out["tools"].append((tool, shutil.which(tool) is not None, why))
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--strict", action="store_true",
                    help="also fail when optional pieces are missing")
    args = ap.parse_args()
    res = check()
    strict_ok = res["ok"]
    for section, rows in (("required", res["required"]),
                          ("optional", res["optional"]), ("tools", res["tools"])):
        for name, ok, why in rows:
            mark = "ok  " if ok else ("MISS" if section == "required" else "miss")
            print(f"[{mark}] {section:8s} {name:14s} — {why}")
            if not ok and section != "required" and args.strict:
                strict_ok = False
    print("client setup:", "OK" if (strict_ok if args.strict else res["ok"]) else "INCOMPLETE")
    return 0 if (strict_ok if args.strict else res["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
