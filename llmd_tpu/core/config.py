"""Plugin-graph configuration system (EndpointPickerConfig equivalent).

Parity: reference docs/architecture/core/router/epp/configuration.md:1-129 — a single YAML
document declares plugin instances (nodes) and wires them into schedulingProfiles,
flowControl, saturationDetector, dataLayer, parser and featureGates. Validation rules
(configuration.md:52-56): all references resolve, instance names unique, extractor graph
acyclic. Defaulting tiers (configuration.md:150-166, 349-375): a `default` profile is
auto-created from all scorer/filter instances when none is declared, and a `max-score`
picker is auto-injected into any profile lacking one. Config is read once at startup.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import yaml


class ConfigError(ValueError):
    pass


@dataclass
class PluginSpec:
    name: str
    type: str
    params: dict[str, Any] = field(default_factory=dict)


@dataclass
class ProfilePluginRef:
    plugin_ref: str
    weight: float = 1.0


@dataclass
class SchedulingProfileSpec:
    name: str
    plugins: list[ProfilePluginRef] = field(default_factory=list)


@dataclass
class PriorityBandSpec:
    """Flow-control priority band (flow-control.md:242-254)."""

    priority: int
    name: str = ""
    max_bytes: int = 1 << 30
    max_requests: int = 10000
    fairness_policy: str = "round-robin"  # or "global-strict"
    ordering_policy: str = "fcfs"  # or "edf", "slo-deadline"
    ttl_s: float = 60.0


@dataclass
class FlowControlSpec:
    enabled: bool = False
    bands: list[PriorityBandSpec] = field(default_factory=list)
    saturation_detector: str = "utilization-detector"


@dataclass
class FrameworkConfig:
    plugins: list[PluginSpec] = field(default_factory=list)
    scheduling_profiles: list[SchedulingProfileSpec] = field(default_factory=list)
    profile_handler: str = "single-profile"
    flow_control: FlowControlSpec = field(default_factory=FlowControlSpec)
    parser: str = "openai-parser"
    feature_gates: dict[str, bool] = field(default_factory=dict)
    data_sources: list[PluginSpec] = field(default_factory=list)
    raw: dict[str, Any] = field(default_factory=dict)

    def plugin(self, name: str) -> PluginSpec:
        for p in self.plugins:
            if p.name == name:
                return p
        raise ConfigError(f"unknown plugin ref {name!r}")

    @classmethod
    def from_yaml(cls, text: str, known_types: Optional[set[str]] = None) -> "FrameworkConfig":
        doc = yaml.safe_load(text) or {}
        return cls.from_dict(doc, known_types)

    @classmethod
    def from_dict(cls, doc: dict[str, Any], known_types: Optional[set[str]] = None) -> "FrameworkConfig":
        cfg = cls(raw=doc)
        for p in doc.get("plugins", []) or []:
            if "type" not in p:
                raise ConfigError(f"plugin missing type: {p}")
            cfg.plugins.append(
                PluginSpec(name=p.get("name", p["type"]), type=p["type"],
                           params=p.get("params", {}) or {})
            )
        for prof in doc.get("schedulingProfiles", []) or []:
            refs = [
                ProfilePluginRef(plugin_ref=r["pluginRef"], weight=float(r.get("weight", 1.0)))
                for r in prof.get("plugins", []) or []
            ]
            cfg.scheduling_profiles.append(
                SchedulingProfileSpec(name=prof.get("name", "default"), plugins=refs)
            )
        cfg.profile_handler = doc.get("profileHandler", "single-profile")
        cfg.parser = doc.get("parser", "openai-parser")
        cfg.feature_gates = dict(doc.get("featureGates", {}) or {})
        fc = doc.get("flowControl", {}) or {}
        cfg.flow_control = FlowControlSpec(
            enabled=bool(fc.get("enabled", cfg.feature_gates.get("flowControl", False))),
            saturation_detector=fc.get("saturationDetector", "utilization-detector"),
            bands=[
                PriorityBandSpec(
                    priority=int(b["priority"]), name=b.get("name", str(b["priority"])),
                    max_bytes=int(b.get("maxBytes", 1 << 30)),
                    max_requests=int(b.get("maxRequests", 10000)),
                    fairness_policy=b.get("fairnessPolicy", "round-robin"),
                    ordering_policy=b.get("orderingPolicy", "fcfs"),
                    ttl_s=float(b.get("ttl", 60.0)),
                )
                for b in fc.get("bands", []) or []
            ],
        )
        for s in (doc.get("dataLayer") or {}).get("sources") or []:
            cfg.data_sources.append(
                PluginSpec(name=s.get("name", s["type"]), type=s["type"],
                           params=s.get("params", {}) or {})
            )
        cfg._validate(known_types)
        cfg._apply_defaults()
        return cfg

    def _validate(self, known_types: Optional[set[str]]) -> None:
        names = [p.name for p in self.plugins]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ConfigError(f"duplicate plugin names: {sorted(dupes)}")
        if known_types is not None:
            for p in self.plugins + self.data_sources:
                if p.type not in known_types:
                    raise ConfigError(f"unknown plugin type {p.type!r} (plugin {p.name!r})")
        nameset = set(names)
        for prof in self.scheduling_profiles:
            for ref in prof.plugins:
                if ref.plugin_ref not in nameset:
                    raise ConfigError(
                        f"profile {prof.name!r} references unknown plugin {ref.plugin_ref!r}"
                    )
        profs = [p.name for p in self.scheduling_profiles]
        if len(profs) != len(set(profs)):
            raise ConfigError("duplicate scheduling profile names")
        bands = [b.priority for b in self.flow_control.bands]
        if len(bands) != len(set(bands)):
            raise ConfigError("duplicate flow-control band priorities")

    def _apply_defaults(self) -> None:
        # Auto 'default' profile over every declared plugin (configuration.md:150-166).
        if not self.scheduling_profiles:
            self.scheduling_profiles.append(
                SchedulingProfileSpec(
                    name="default",
                    plugins=[ProfilePluginRef(plugin_ref=p.name) for p in self.plugins],
                )
            )
        # Auto max-score picker injection (scheduling.md:104-108).
        picker_types = {"max-score-picker", "random-picker", "weighted-random-picker"}
        by_name = {p.name: p for p in self.plugins}
        for prof in self.scheduling_profiles:
            has_picker = any(
                by_name.get(r.plugin_ref) and by_name[r.plugin_ref].type in picker_types
                for r in prof.plugins
            )
            if not has_picker:
                if "max-score-picker" not in by_name:
                    spec = PluginSpec(name="max-score-picker", type="max-score-picker")
                    self.plugins.append(spec)
                    by_name[spec.name] = spec
                prof.plugins.append(ProfilePluginRef(plugin_ref="max-score-picker"))


def load_config(path: str, known_types: Optional[set[str]] = None) -> FrameworkConfig:
    with open(path) as f:
        return FrameworkConfig.from_yaml(f.read(), known_types)
