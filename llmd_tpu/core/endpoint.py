"""Endpoint + thread-safe typed attribute map (the Data Layer's unit of state).

Parity: reference docs/architecture/core/router/epp/datalayer.md:5-91 — each endpoint
(one per ``podIP:port``; DP ranks surface as distinct endpoints, scheduling.md:48) carries
a thread-safe typed attribute map written by Extractors and read by scheduler plugins.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional


class EndpointRole(str, Enum):
    BOTH = "both"
    PREFILL = "prefill"
    DECODE = "decode"


class AttributeMap:
    """Thread-safe typed attribute store (datalayer.md 'Attribute' runtime)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: dict[str, Any] = {}
        self._stamp: dict[str, float] = {}

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value
            self._stamp[key] = time.monotonic()

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._data.get(key, default)

    def age(self, key: str) -> float:
        """Seconds since `key` was last written; +inf if never."""
        with self._lock:
            ts = self._stamp.get(key)
        return float("inf") if ts is None else time.monotonic() - ts

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._data)


@dataclass
class Endpoint:
    """A routable model-server endpoint (pod/rank)."""

    address: str  # "ip:port"
    name: str = ""
    role: EndpointRole = EndpointRole.BOTH
    labels: dict[str, str] = field(default_factory=dict)
    engine_type: str = "llmd-tpu"  # llm-d.ai/engine-type label analogue
    attrs: AttributeMap = field(default_factory=AttributeMap)
    ready: bool = True

    def __post_init__(self) -> None:
        if not self.name:
            self.name = self.address

    @property
    def host(self) -> str:
        if ":" not in self.address:
            return self.address
        return self.address.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        """Port part of the address; 0 when absent/unparseable (portless or bare IPv6)."""
        if ":" not in self.address:
            return 0
        try:
            return int(self.address.rsplit(":", 1)[1])
        except ValueError:
            return 0

    # Convenience accessors for the standard metrics (metrics_contract.StdMetric keys).
    def metric(self, key: str, default: float = 0.0) -> float:
        v = self.attrs.get(key)
        return default if v is None else float(v)

    def mark_scrape_failed(self) -> None:
        """Called by the metrics poller when this endpoint's scrape fails: the
        last-known metrics stay readable but are flagged stale so consumers
        (breaker passive health, /v1/models aggregation) can discount them."""
        self.attrs.put("scrape_failed", True)

    def mark_scrape_ok(self) -> None:
        self.attrs.put("scrape_failed", False)
        self.attrs.put("last_poll_ok", time.monotonic())

    def stale(self, max_age_s: float = 10.0) -> bool:
        """True when the last scrape failed, or no successful scrape landed
        within ``max_age_s`` (and at least one scrape was ever attempted —
        a never-polled endpoint, e.g. unit tests without a poller, is not
        stale)."""
        if self.attrs.get("scrape_failed"):
            return True
        age = self.attrs.age("last_poll_ok")
        return age != float("inf") and age > max_age_s

    def __hash__(self) -> int:
        return hash(self.address)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Endpoint) and other.address == self.address


class EndpointPool:
    """Live set of endpoints (InferencePool analogue, inferencepool.md §Dynamic Membership).

    Membership changes arrive from a discovery source (static file / k8s watch); consumers
    (scheduler, pollers) read a consistent snapshot.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._eps: dict[str, Endpoint] = {}
        self._listeners: list[Any] = []  # guarded-by: _lock

    def upsert(self, ep: Endpoint) -> None:
        with self._lock:
            existing = self._eps.get(ep.address)
            if existing is not None:
                existing.role = ep.role
                existing.labels = ep.labels
                existing.ready = ep.ready
                return
            self._eps[ep.address] = ep
            listeners = list(self._listeners)
        for fn in listeners:  # callbacks run outside the lock
            fn("added", ep)

    def remove(self, address: str) -> Optional[Endpoint]:
        with self._lock:
            ep = self._eps.pop(address, None)
            listeners = list(self._listeners) if ep is not None else []
        if ep is not None:
            for fn in listeners:  # callbacks run outside the lock
                fn("removed", ep)
        return ep

    def list(self, role: Optional[EndpointRole] = None) -> list[Endpoint]:
        with self._lock:
            eps = [e for e in self._eps.values() if e.ready]
        if role is None or role == EndpointRole.BOTH:
            return eps
        return [e for e in eps if e.role in (role, EndpointRole.BOTH)]

    def get(self, address: str) -> Optional[Endpoint]:
        with self._lock:
            return self._eps.get(address)

    def subscribe(self, fn: Any) -> None:
        """fn(event: 'added'|'removed', endpoint) — endpoint-notification-source analogue."""
        with self._lock:
            self._listeners.append(fn)

    def unsubscribe(self, fn: Any) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._eps)
