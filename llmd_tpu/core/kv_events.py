"""KV-event schema + wire format (ZMQ PUB/SUB, msgpack payload).

Parity: reference docs/architecture/advanced/kv-management/kv-indexer.md:59-63 — engines
publish BlockStored (chained parent hash, token chunk, LoRA, multimodal extra keys, tier),
BlockRemoved, AllBlocksCleared whenever KV-cache state changes. Topic format
``kv@<pod_ip:port>@<model>`` (precise-prefix-cache-routing/README.md:300-307). Delivery is
either centralized (router binds, engines connect) or pod-discovery (each engine binds;
router subscribes per pod → active-active HA), kv-indexer.md:67-87.

Block-key chaining: key_i = H(key_{i-1} ‖ tokens_i ‖ lora ‖ mm_extra), so a block is only
reusable behind its unbroken prefix chain.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence, Union

import msgpack

MEDIUM_HBM = "gpu"  # tier names kept from the reference for scorer-weight parity
MEDIUM_CPU = "cpu"
MEDIUM_FS = "fs"


@dataclass
class BlockStored:
    block_hashes: list[int]
    parent_block_hash: Optional[int]
    token_ids: list[int]  # concatenated token chunk covered by these blocks
    block_size: int
    lora_id: Optional[str] = None
    medium: str = MEDIUM_HBM
    # Multimodal extra keys folded into hashing (kv-indexer.md:146-151).
    extra_keys: list[bytes] = field(default_factory=list)


@dataclass
class BlockRemoved:
    block_hashes: list[int]
    medium: str = MEDIUM_HBM


@dataclass
class AllBlocksCleared:
    pass


KVEvent = Union[BlockStored, BlockRemoved, AllBlocksCleared]

_TAGS = {"BlockStored": 0, "BlockRemoved": 1, "AllBlocksCleared": 2}


def kv_topic(pod_address: str, model: str) -> str:
    return f"kv@{pod_address}@{model}"


def encode_event_batch(events: Sequence[KVEvent], seq: int = 0) -> bytes:
    """Encode an event batch: msgpack [seq, [tagged event, ...]]."""
    rows = []
    for ev in events:
        if isinstance(ev, BlockStored):
            rows.append([
                _TAGS["BlockStored"], ev.block_hashes, ev.parent_block_hash,
                ev.token_ids, ev.block_size, ev.lora_id, ev.medium, ev.extra_keys,
            ])
        elif isinstance(ev, BlockRemoved):
            rows.append([_TAGS["BlockRemoved"], ev.block_hashes, ev.medium])
        elif isinstance(ev, AllBlocksCleared):
            rows.append([_TAGS["AllBlocksCleared"]])
        else:  # pragma: no cover
            raise TypeError(f"unknown event {ev!r}")
    return msgpack.packb([seq, rows], use_bin_type=True)


def decode_event_batch(data: bytes) -> tuple[int, list[KVEvent]]:
    seq, rows = msgpack.unpackb(data, raw=False)
    out: list[KVEvent] = []
    for row in rows:
        tag = row[0]
        if tag == _TAGS["BlockStored"]:
            out.append(BlockStored(
                block_hashes=list(row[1]), parent_block_hash=row[2],
                token_ids=list(row[3]), block_size=row[4], lora_id=row[5],
                medium=row[6], extra_keys=list(row[7]),
            ))
        elif tag == _TAGS["BlockRemoved"]:
            out.append(BlockRemoved(block_hashes=list(row[1]), medium=row[2]))
        elif tag == _TAGS["AllBlocksCleared"]:
            out.append(AllBlocksCleared())
    return seq, out


def hash_block_tokens(
    parent_hash: Optional[int],
    token_ids: Sequence[int],
    lora_id: Optional[str] = None,
    extra_keys: Iterable[bytes] = (),
) -> int:
    """Content hash of one KV block, chained to its parent (dual-key design).

    Stable across processes (sha256-based, not Python hash()) so router-side computed keys
    match engine-published ones.
    """
    h = hashlib.sha256()
    h.update(struct.pack("<q", -1 if parent_hash is None else parent_hash))
    h.update(struct.pack(f"<{len(token_ids)}i", *token_ids))
    if lora_id:
        h.update(lora_id.encode())
    for k in extra_keys:
        h.update(k)
    return struct.unpack("<q", h.digest()[:8])[0]


def block_keys_for_tokens(
    token_ids: Sequence[int],
    block_size: int,
    lora_id: Optional[str] = None,
    mm_hashes: Iterable[bytes] = (),
) -> list[int]:
    """Chained block keys for a full token sequence (only complete blocks are keyed)."""
    keys: list[int] = []
    parent: Optional[int] = None
    mm = list(mm_hashes)
    for i in range(0, len(token_ids) - len(token_ids) % block_size, block_size):
        parent = hash_block_tokens(parent, token_ids[i : i + block_size], lora_id, mm)
        keys.append(parent)
    return keys
