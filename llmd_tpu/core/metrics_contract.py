"""Model-server metrics contract + engine-name mapping.

Parity: reference docs/architecture/core/model-servers.md:38-52 — the router scrapes a
Prometheus endpoint and maps engine-specific metric names (vLLM/SGLang/trtllm/our own
engine) onto standard keys. The LoRA metric contract is model-servers.md:55-75.
"""

from __future__ import annotations

import re
from typing import Iterable


class StdMetric:
    """Standard attribute keys written into Endpoint.attrs by the core-metrics-extractor."""

    QUEUED_REQUESTS = "total_queued_requests"
    RUNNING_REQUESTS = "total_running_requests"
    KV_UTILIZATION = "kv_cache_utilization"  # fraction [0,1]
    BLOCK_SIZE = "kv_block_size"  # tokens per KV block
    NUM_BLOCKS = "kv_num_blocks"  # total HBM KV blocks
    LORA_INFO = "lora_info"  # dict: max_lora, running, waiting
    WAITING_TOKENS = "waiting_tokens"  # for token-load-scorer


# engine-type → {standard key: (metric name, optional label name)}
# A label name means the value is carried on a labeled info-gauge (cache_config_info).
METRIC_MAPPINGS: dict[str, dict[str, tuple[str, str | None]]] = {
    "vllm": {
        StdMetric.QUEUED_REQUESTS: ("vllm:num_requests_waiting", None),
        StdMetric.RUNNING_REQUESTS: ("vllm:num_requests_running", None),
        StdMetric.KV_UTILIZATION: ("vllm:kv_cache_usage_perc", None),
        StdMetric.BLOCK_SIZE: ("vllm:cache_config_info", "block_size"),
        StdMetric.NUM_BLOCKS: ("vllm:cache_config_info", "num_gpu_blocks"),
    },
    "sglang": {
        StdMetric.QUEUED_REQUESTS: ("sglang:num_queue_reqs", None),
        StdMetric.RUNNING_REQUESTS: ("sglang:num_running_reqs", None),
        StdMetric.KV_UTILIZATION: ("sglang:token_usage", None),
        StdMetric.BLOCK_SIZE: ("sglang:cache_config_info", "page_size"),
        StdMetric.NUM_BLOCKS: ("sglang:cache_config_info", "num_pages"),
    },
    "trtllm-serve": {
        StdMetric.QUEUED_REQUESTS: ("trtllm_num_requests_waiting", None),
        StdMetric.RUNNING_REQUESTS: ("trtllm_num_requests_running", None),
        StdMetric.KV_UTILIZATION: ("trtllm_kv_cache_utilization", None),
        StdMetric.BLOCK_SIZE: ("trtllm_kv_cache_tokens_per_block", None),
        StdMetric.NUM_BLOCKS: ("trtllm_kv_cache_max_blocks", None),
    },
    # Our own TPU engine publishes the vLLM-compatible names so existing llm-d routers
    # and dashboards work unchanged, plus llmd_tpu:* duplicates.
    "llmd-tpu": {
        StdMetric.QUEUED_REQUESTS: ("vllm:num_requests_waiting", None),
        StdMetric.RUNNING_REQUESTS: ("vllm:num_requests_running", None),
        StdMetric.KV_UTILIZATION: ("vllm:kv_cache_usage_perc", None),
        StdMetric.BLOCK_SIZE: ("vllm:cache_config_info", "block_size"),
        StdMetric.NUM_BLOCKS: ("vllm:cache_config_info", "num_gpu_blocks"),
    },
}

LORA_METRIC = "vllm:lora_requests_info"

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> list[tuple[str, dict[str, str], float]]:
    """Minimal Prometheus text-format parser: (name, labels, value) per sample."""
    out: list[tuple[str, dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.append((m.group("name"), labels, value))
    return out


def map_engine_metrics(engine_type: str, samples: Iterable[tuple[str, dict[str, str], float]]):
    """Map scraped samples to standard keys → values (core-metrics-extractor).

    LoRA info-gauge handling follows model-servers.md:64-75: value is a timestamp; the
    freshest sample's labels carry max_lora / running / waiting adapter lists.
    """
    mapping = METRIC_MAPPINGS.get(engine_type, METRIC_MAPPINGS["vllm"])
    by_metric: dict[str, list[tuple[dict[str, str], float]]] = {}
    for name, labels, value in samples:
        by_metric.setdefault(name, []).append((labels, value))

    out: dict[str, object] = {}
    for std_key, (metric_name, label_name) in mapping.items():
        rows = by_metric.get(metric_name)
        if not rows:
            continue
        if label_name is None:
            out[std_key] = rows[-1][1]
        else:
            for labels, _ in rows:
                if label_name in labels:
                    try:
                        out[std_key] = float(labels[label_name])
                    except ValueError:
                        pass
    lora_rows = by_metric.get(LORA_METRIC)
    if lora_rows:
        labels, _ = max(lora_rows, key=lambda r: r[1])  # latest timestamp wins
        out[StdMetric.LORA_INFO] = {
            "max_lora": int(float(labels.get("max_lora", "0") or 0)),
            "running": [a.strip() for a in labels.get("running_lora_adapters", "").split(",") if a.strip()],
            "waiting": [a.strip() for a in labels.get("waiting_lora_adapters", "").split(",") if a.strip()],
        }
    return out
