"""Inference request model + the llm-d HTTP header contract.

Parity targets:
- header names: reference docs/api-reference/epp-http-headers.md:5-20
- InferenceRequest fields: reference docs/architecture/core/router/epp/request-handling.md:50-86
- flow-control outcome → HTTP status map: reference
  docs/architecture/core/router/epp/flow-control.md:310-344
"""

from __future__ import annotations

import hashlib
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional, Sequence

# HTTP header contract (x-llm-d-*), kept verbatim for drop-in client compatibility.
HDR_OBJECTIVE = "x-llm-d-inference-objective"
HDR_FAIRNESS_ID = "x-llm-d-inference-fairness-id"
HDR_MODEL_REWRITE = "x-llm-d-model-name-rewrite"
HDR_SLO_TTFT_MS = "x-llm-d-slo-ttft-ms"
HDR_SLO_TPOT_MS = "x-llm-d-slo-tpot-ms"
HDR_PREFILLER_HOST_PORT = "x-prefiller-host-port"
# End-to-end deadline contract (observability/resilience.md): seconds of total
# budget. The router decrements it across flow-control wait + scheduling and
# forwards the REMAINDER under the same name, so the engine always sees how
# much budget the client has left, not the original figure.
HDR_REQUEST_TIMEOUT = "x-request-timeout"
# Tenant identity for per-tenant accounting + SLO attainment
# (observability/slo-attribution.md). Absent/invalid → "anon". The router
# forwards the clamped value so engine-side timelines carry the same tenant.
HDR_TENANT = "x-llm-d-tenant"

# Identifier hygiene: both the tenant label and client-supplied request ids
# become flight-recorder keys and metric/exemplar label values, so hostile
# headers must not be able to bloat either.
_IDENT_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")
MAX_TENANT_LEN = 64
MAX_REQUEST_ID_LEN = 128


def clamp_tenant(raw: Optional[str]) -> str:
    """Validate a tenant header value: bounded length, [A-Za-z0-9._-] only.
    Anything else collapses to "anon" — an invalid tenant must not mint a
    fresh metric label set."""
    if not raw:
        return "anon"
    v = raw.strip()
    if not v or len(v) > MAX_TENANT_LEN or not set(v) <= _IDENT_CHARS:
        return "anon"
    return v


def clamp_request_id(raw: Optional[str]) -> str:
    """Validate a client x-request-id; invalid/oversized values fall back to
    a generated id rather than keying recorder entries on hostile bytes."""
    if raw:
        v = raw.strip()
        if v and len(v) <= MAX_REQUEST_ID_LEN and set(v) <= _IDENT_CHARS:
            return v
    return uuid.uuid4().hex


def media_url_of_part(part: Any) -> "tuple[Optional[str], Optional[str]]":
    """(kind, payload-url-or-data) of a media content part, else (None, None).

    THE one media-kind→payload extraction — _mm_hash, the encode module's
    is_media_part/media_bytes_from_part, and flatten rendering all build on it;
    separate copies drifted once (router hashing media the engine rejected,
    silently zeroing prefix-cache affinity) and must not exist again."""
    if not isinstance(part, dict):
        return None, None
    kind = part.get("type")
    if kind == "image_url":
        url = (part.get("image_url") or {}).get("url", "")
    elif kind in ("input_audio", "video_url", "audio_url"):
        sub = part.get(kind) or {}
        url = sub.get("url", "") or sub.get("data", "")
    else:
        return None, None
    return (kind, str(url)) if url else (kind, None)


def _is_inline_payload(url: Optional[str]) -> bool:
    """THE inline-media rule (one definition: every media predicate/identity
    derives from it or router↔engine cache-key agreement drifts)."""
    return url is not None and url.startswith("data:")


def part_is_inline_media(part: Any) -> bool:
    """True for parts the serving stack treats as media: inline ``data:`` URIs
    (no egress — remote URLs are text from the cache's point of view)."""
    return _is_inline_payload(media_url_of_part(part)[1])


def _mm_hash(part: dict[str, Any]) -> Optional[bytes]:
    """Cache identity of one INLINE media part (image_url / input_audio...).

    The reference folds these into KV block keys (kv-indexer.md:14,146-151) so
    two prompts with different images never share cache entries. Only parts the
    engine itself treats as media (inline data: URIs) get an identity —
    hashing anything broader breaks router↔engine key agreement."""
    kind, url = media_url_of_part(part)
    if not _is_inline_payload(url):
        return None
    # kind folds in: the same bytes as image vs video are different cache
    # identities (modality-specific encoders produce different embeddings)
    return hashlib.sha256(f"{kind}:".encode() + url.encode()).digest()


def flatten_messages(messages: Sequence[dict[str, Any]]) -> str:
    """Canonical chat→text flattening shared by router, engine, and test fixture.

    Router-side block keys are computed over this rendering, so every component MUST use
    this one helper (divergence silently breaks prefix-cache scoring).

    Multimodal content parts render as ``<image:hash16>`` placeholders — the media
    identity lands IN the token stream at its position, so engine-side block hashes
    (computed over tokens) distinguish different images without extra plumbing,
    mirroring the reference's mm-extra-keys fold (kv-indexer.md:146-151).
    """
    out = []
    for m in messages:
        content = m.get("content", "")
        if isinstance(content, list):
            pieces = []
            for part in content:
                # Clients may send bare strings in the parts list; treat them as
                # text instead of 500ing on part.get.
                if not isinstance(part, dict):
                    pieces.append(str(part))
                elif part.get("type") == "text":
                    pieces.append(part.get("text", ""))
                else:
                    # rendering identity covers ANY payload (remote URLs too —
                    # different links must render differently); the mm
                    # extra-key fold (_mm_hash) stays inline-media-only
                    kind, url = media_url_of_part(part)
                    kind = kind or part.get("type", "media")
                    pieces.append(
                        f"<{kind}:{hashlib.sha256(url.encode()).hexdigest()[:16]}>"
                        if url else f"<{kind}>")
            content = " ".join(pieces)
        out.append(f"{m.get('role', '')}: {content}")
    return "\n".join(out)


def mm_hashes_from_messages(messages: Sequence[dict[str, Any]]) -> list[bytes]:
    """All multimodal content hashes in order of appearance."""
    hashes: list[bytes] = []
    for m in messages:
        content = m.get("content")
        if isinstance(content, list):
            for part in content:
                if not isinstance(part, dict):
                    continue
                h = _mm_hash(part)
                if h is not None:
                    hashes.append(h)
    return hashes


@dataclass
class SamplingParams:
    """OpenAI-compatible sampling parameters understood by the engine."""

    max_tokens: int = 16
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    min_p: float = 0.0
    stop: Sequence[str] = ()
    stop_token_ids: Sequence[int] = ()
    seed: Optional[int] = None
    logprobs: Optional[int] = None
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    ignore_eos: bool = False
    n: int = 1
    # Structured outputs (llmd_tpu/structured): guided_* follow vLLM's guided
    # decoding surface, response_format the OpenAI one ({"type": "json_object"
    # | "json_schema", ...}). The engine compiles these to a token DFA whose
    # per-step allow-mask rides the same device bias-add as logit_bias.
    guided_choice: Optional[Sequence[str]] = None
    guided_regex: Optional[str] = None
    response_format: Optional[dict] = None
    # OpenAI logit_bias: token id -> additive bias in [-100, 100]; -100 bans.
    logit_bias: Optional[dict] = None

    def greedy(self) -> bool:
        return self.temperature == 0.0

    def constrained(self) -> bool:
        """True when decoding needs the biased sampler (grammar or bias)."""
        return bool(self.guided_choice or self.guided_regex or self.logit_bias
                    or (isinstance(self.response_format, dict)
                        and self.response_format.get("type")
                        in ("json_object", "json_schema")))


class RequestOutcome(str, Enum):
    """Flow-control dispatch outcomes and their HTTP mapping.

    Reference flow-control.md:310-344: queue-full → 429, TTL-expiry/disconnect → 503,
    shutdown → 500, dispatched → forwarded.
    """

    DISPATCHED = "dispatched"
    REJECTED_CAPACITY = "rejected_capacity"  # → 429
    EVICTED_TTL = "evicted_ttl"  # → 503
    EVICTED_DISCONNECT = "evicted_disconnect"  # → 503
    EVICTED_SHUTDOWN = "evicted_shutdown"  # → 500
    EVICTED_DEADLINE = "evicted_deadline"  # → 504 (client budget spent in queue)

    @property
    def http_status(self) -> int:
        return {
            RequestOutcome.DISPATCHED: 200,
            RequestOutcome.REJECTED_CAPACITY: 429,
            RequestOutcome.EVICTED_TTL: 503,
            RequestOutcome.EVICTED_DISCONNECT: 503,
            RequestOutcome.EVICTED_SHUTDOWN: 500,
            RequestOutcome.EVICTED_DEADLINE: 504,
        }[self]


@dataclass
class InferenceRequest:
    """A parsed inference request flowing through the router.

    Built by a Parser (openai/grpc/passthrough — request-handling.md:50-73); enriched by
    DataProducers (token ids, prefix-block keys, predicted latency); consumed by the
    Filter→Score→Pick scheduler.
    """

    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    model: str = ""
    # One of prompt (text) / messages (chat) / token_ids (pre-tokenized).
    prompt: Optional[str] = None
    messages: Optional[list[dict[str, Any]]] = None
    token_ids: Optional[list[int]] = None
    sampling: SamplingParams = field(default_factory=SamplingParams)
    streaming: bool = False
    arrival_time: float = field(default_factory=time.monotonic)

    # Header-derived routing state.
    objective: Optional[str] = None  # InferenceObjective name → priority band
    fairness_id: str = ""  # FlowKey = (fairness_id, priority)
    tenant: str = "anon"  # clamped x-llm-d-tenant (accounting + SLO gauges)
    priority: int = 0
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # Total end-to-end budget in seconds (x-request-timeout header or router
    # default). The deadline is absolute: arrival_time + timeout_s, so queueing
    # and scheduling time decrement the budget without extra bookkeeping.
    timeout_s: Optional[float] = None
    lora_adapter: Optional[str] = None
    # Multimodal content hashes folded into block keys (kv-indexer.md:146-151).
    mm_hashes: list[bytes] = field(default_factory=list)

    # Producer-attached state (typed scratch shared across plugins).
    state: dict[str, Any] = field(default_factory=dict)

    # Approximate request size for flow-control byte accounting.
    byte_size: int = 0

    def prompt_text(self) -> str:
        if self.prompt is not None:
            return self.prompt
        if self.messages is not None:
            return flatten_messages(self.messages)
        return ""

    def flow_key(self) -> tuple[str, int]:
        return (self.fairness_id, self.priority)

    def deadline(self) -> Optional[float]:
        """Absolute monotonic deadline, or None when no budget was set."""
        if self.timeout_s is None:
            return None
        return self.arrival_time + self.timeout_s

    def remaining_s(self, now: Optional[float] = None) -> Optional[float]:
        """Budget left (may be negative once expired); None = unbounded."""
        dl = self.deadline()
        if dl is None:
            return None
        return dl - (time.monotonic() if now is None else now)

    @classmethod
    def from_headers(cls, headers: dict[str, str], **kw: Any) -> "InferenceRequest":
        req = cls(**kw)
        get = {k.lower(): v for k, v in headers.items()}.get
        req.objective = get(HDR_OBJECTIVE)
        req.fairness_id = get(HDR_FAIRNESS_ID, "") or ""
        req.tenant = clamp_tenant(get(HDR_TENANT))
        # Malformed client-supplied SLO headers are ignored, not fatal.
        for hdr, attr in ((HDR_SLO_TTFT_MS, "slo_ttft_ms"), (HDR_SLO_TPOT_MS, "slo_tpot_ms")):
            raw = get(hdr)
            if raw:
                try:
                    setattr(req, attr, float(raw))
                except ValueError:
                    pass
        raw = get(HDR_REQUEST_TIMEOUT)
        if raw:
            try:
                t = float(raw)
                if t > 0:
                    req.timeout_s = t
            except ValueError:
                pass
        return req
