"""Core contracts shared by every subsystem (SURVEY.md §7 phase 1).

Pure-Python, hardware-free: request/response model, endpoint attribute model,
the model-server metrics contract, the KV-event schema, and the plugin-graph
configuration system.
"""

from llmd_tpu.core.request import (  # noqa: F401
    InferenceRequest,
    SamplingParams,
    RequestOutcome,
    HDR_OBJECTIVE,
    HDR_FAIRNESS_ID,
    HDR_MODEL_REWRITE,
    HDR_SLO_TTFT_MS,
    HDR_SLO_TPOT_MS,
    HDR_PREFILLER_HOST_PORT,
)
from llmd_tpu.core.endpoint import Endpoint, AttributeMap, EndpointRole  # noqa: F401
from llmd_tpu.core.metrics_contract import (  # noqa: F401
    StdMetric,
    METRIC_MAPPINGS,
    map_engine_metrics,
)
from llmd_tpu.core.kv_events import (  # noqa: F401
    BlockStored,
    BlockRemoved,
    AllBlocksCleared,
    encode_event_batch,
    decode_event_batch,
    kv_topic,
)
from llmd_tpu.core.config import FrameworkConfig, ConfigError  # noqa: F401
