"""API-surface schema objects: the reference's CRDs as validated config types.

Parity targets (each section cites its reference spec):
- ``InferencePool`` — inference.networking.k8s.io/v1: selector, targetPorts
  (≤ 8, one endpoint per podIP:port — the DP-rank fan-out), endpointPickerRef
  with failureMode FailOpen|FailClose
  (/root/reference/docs/api-reference/inferencepool.md:1-60).
- ``InferenceObjective`` — llm-d.ai/v1alpha2: priority + poolRef
  (docs/api-reference/inferenceobjective.md:1-48).
- ``InferenceModelRewrite`` — weighted model-name targets for canary/A-B
  (docs/api-reference/inferencemodelrewrite.md:1-66).
- ``VariantAutoscaling`` — llmd.ai/v1alpha1 (autoscaling/wva.md:205-237).

These are plain dataclasses loadable from k8s-shaped YAML/JSON manifests
(apiVersion/kind/metadata/spec), so the same documents deploy to a cluster and
configure the no-Kubernetes standalone mode. ``load_manifests`` is the entry:
it validates kinds, field types, and cross-object references.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

MAX_TARGET_PORTS = 8


class ManifestError(ValueError):
    pass


@dataclass
class EndpointPickerRef:
    name: str
    port: int = 9002
    failure_mode: str = "FailClose"  # FailOpen | FailClose

    def __post_init__(self) -> None:
        if self.failure_mode not in ("FailOpen", "FailClose"):
            raise ManifestError(
                f"endpointPickerRef.failureMode must be FailOpen|FailClose, "
                f"got {self.failure_mode!r}")


@dataclass
class InferencePool:
    name: str
    selector: dict[str, str]
    target_ports: list[int]
    endpoint_picker_ref: Optional[EndpointPickerRef] = None
    namespace: str = "default"

    def __post_init__(self) -> None:
        if not self.selector:
            raise ManifestError(f"InferencePool {self.name}: empty selector")
        if not self.target_ports:
            raise ManifestError(f"InferencePool {self.name}: no targetPorts")
        if len(self.target_ports) > MAX_TARGET_PORTS:
            raise ManifestError(
                f"InferencePool {self.name}: {len(self.target_ports)} targetPorts "
                f"exceeds the {MAX_TARGET_PORTS}-port limit")
        if len(set(self.target_ports)) != len(self.target_ports):
            raise ManifestError(f"InferencePool {self.name}: duplicate targetPorts")

    @property
    def failure_mode(self) -> str:
        return (self.endpoint_picker_ref.failure_mode
                if self.endpoint_picker_ref else "FailClose")

    @classmethod
    def from_manifest(cls, doc: dict) -> "InferencePool":
        spec = doc.get("spec", {})
        meta = doc.get("metadata", {})
        ports = [
            int(p["number"] if isinstance(p, dict) else p)
            for p in spec.get("targetPorts", [])
        ]
        epr = spec.get("endpointPickerRef")
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            selector=dict(spec.get("selector", {}).get("matchLabels",
                                                       spec.get("selector", {}))),
            target_ports=ports,
            endpoint_picker_ref=EndpointPickerRef(
                name=epr.get("name", ""),
                port=int(epr.get("port", 9002)),
                failure_mode=epr.get("failureMode", "FailClose"),
            ) if epr else None,
        )


@dataclass
class InferenceObjective:
    name: str
    priority: int
    pool_ref: str
    namespace: str = "default"

    @classmethod
    def from_manifest(cls, doc: dict) -> "InferenceObjective":
        spec = doc.get("spec", {})
        meta = doc.get("metadata", {})
        pool = spec.get("poolRef", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            priority=int(spec.get("priority", 0)),
            pool_ref=pool.get("name", "") if isinstance(pool, dict) else str(pool),
        )


@dataclass
class RewriteTarget:
    model: str
    weight: float = 1.0

    def __post_init__(self) -> None:
        if self.weight < 0:
            raise ManifestError(f"rewrite target {self.model}: negative weight")


@dataclass
class InferenceModelRewrite:
    name: str
    model: str  # client-facing name
    targets: list[RewriteTarget] = field(default_factory=list)
    namespace: str = "default"

    def __post_init__(self) -> None:
        if not self.targets:
            raise ManifestError(f"InferenceModelRewrite {self.name}: no targets")
        if sum(t.weight for t in self.targets) <= 0:
            raise ManifestError(
                f"InferenceModelRewrite {self.name}: zero total weight")

    @classmethod
    def from_manifest(cls, doc: dict) -> "InferenceModelRewrite":
        spec = doc.get("spec", {})
        meta = doc.get("metadata", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            model=spec.get("modelName", meta.get("name", "")),
            targets=[
                RewriteTarget(model=t.get("modelName", t.get("model", "")),
                              weight=float(t.get("weight", 1.0)))
                for t in spec.get("targetModels", spec.get("targets", []))
            ],
        )


@dataclass
class VariantAutoscaling:
    name: str
    model_id: str
    min_replicas: int = 0
    max_replicas: int = 8
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    namespace: str = "default"

    @classmethod
    def from_manifest(cls, doc: dict) -> "VariantAutoscaling":
        spec = doc.get("spec", {})
        meta = doc.get("metadata", {})
        slo = spec.get("slo", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            model_id=spec.get("modelID", ""),
            min_replicas=int(spec.get("minReplicas", 0)),
            max_replicas=int(spec.get("maxReplicas", 8)),
            slo_ttft_ms=slo.get("ttftMs"),
            slo_tpot_ms=slo.get("tpotMs"),
        )


# single registry: kind → (parser, ManifestSet attribute)
_KINDS = {
    "InferencePool": (InferencePool.from_manifest, "pools"),
    "InferenceObjective": (InferenceObjective.from_manifest, "objectives"),
    "InferenceModelRewrite": (InferenceModelRewrite.from_manifest, "rewrites"),
    "VariantAutoscaling": (VariantAutoscaling.from_manifest, "autoscalings"),
}


@dataclass
class ManifestSet:
    pools: list[InferencePool] = field(default_factory=list)
    objectives: list[InferenceObjective] = field(default_factory=list)
    rewrites: list[InferenceModelRewrite] = field(default_factory=list)
    autoscalings: list[VariantAutoscaling] = field(default_factory=list)

    def objectives_map(self) -> dict[str, int]:
        """objective name → priority (RouterServer's objectives input)."""
        return {o.name: o.priority for o in self.objectives}

    def rewrites_map(self) -> dict[str, list[tuple[str, float]]]:
        return {r.model: [(t.model, t.weight) for t in r.targets]
                for r in self.rewrites}


def load_manifests(docs: list[dict]) -> ManifestSet:
    """Parse + cross-validate a list of k8s-shaped manifest documents."""
    out = ManifestSet()
    for doc in docs:
        if not doc:
            continue
        kind = doc.get("kind", "")
        entry = _KINDS.get(kind)
        if entry is None:
            raise ManifestError(f"unknown kind {kind!r}")
        fn, attr = entry
        getattr(out, attr).append(fn(doc))
    pool_names = {p.name for p in out.pools}
    for o in out.objectives:
        if o.pool_ref and pool_names and o.pool_ref not in pool_names:
            raise ManifestError(
                f"InferenceObjective {o.name}: poolRef {o.pool_ref!r} matches no "
                f"InferencePool (have {sorted(pool_names)})")
    return out


def load_manifest_yaml(text: str) -> ManifestSet:
    import yaml

    return load_manifests(list(yaml.safe_load_all(text)))
