"""Batch file store — the Batch Gateway's S3/FS object layer.

Parity: reference `docs/architecture/advanced/batch/batch-gateway.md:11-87` — files
land under tenant-hashed paths (tenant isolation: a tenant id from the auth header
prefixes every object key, so one tenant can never address another's files), JSONL
inputs are validated line-by-line at ingest, and output/error files are written by
the processor at finalize.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
from dataclasses import dataclass
from typing import Iterator, Optional


def tenant_hash(tenant: str) -> str:
    return hashlib.sha256(tenant.encode()).hexdigest()[:16]


@dataclass
class FileMeta:
    id: str
    filename: str
    purpose: str
    bytes: int
    created_at: int
    tenant: str

    def to_openai(self) -> dict:
        return {
            "id": self.id, "object": "file", "bytes": self.bytes,
            "created_at": self.created_at, "filename": self.filename,
            "purpose": self.purpose,
        }


class FileStore:
    """FS-backed file objects under <root>/<tenant_hash>/<file_id>."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _dir(self, tenant: str) -> str:
        d = os.path.join(self.root, tenant_hash(tenant))
        os.makedirs(d, exist_ok=True)
        return d

    def _path(self, tenant: str, file_id: str) -> str:
        # file_id is server-generated (uuid hex); reject anything else so a
        # crafted id can't traverse out of the tenant directory
        if not file_id.startswith("file-") or "/" in file_id or ".." in file_id:
            raise KeyError(file_id)
        return os.path.join(self._dir(tenant), file_id)

    def put(self, tenant: str, filename: str, data: bytes, purpose: str = "batch") -> FileMeta:
        file_id = f"file-{uuid.uuid4().hex}"
        path = self._path(tenant, file_id)
        with open(path, "wb") as f:
            f.write(data)
        meta = FileMeta(id=file_id, filename=filename, purpose=purpose,
                        bytes=len(data), created_at=int(time.time()), tenant=tenant)
        with open(path + ".meta", "w") as f:
            json.dump(meta.__dict__, f)
        return meta

    def get_meta(self, tenant: str, file_id: str) -> Optional[FileMeta]:
        try:
            with open(self._path(tenant, file_id) + ".meta") as f:
                return FileMeta(**json.load(f))
        except (FileNotFoundError, KeyError):
            return None

    def get_content(self, tenant: str, file_id: str) -> Optional[bytes]:
        try:
            with open(self._path(tenant, file_id), "rb") as f:
                return f.read()
        except (FileNotFoundError, KeyError):
            return None

    def delete(self, tenant: str, file_id: str) -> bool:
        try:
            os.remove(self._path(tenant, file_id))
            os.remove(self._path(tenant, file_id) + ".meta")
            return True
        except (FileNotFoundError, KeyError):
            return False

    def list(self, tenant: str) -> list[FileMeta]:
        out = []
        d = self._dir(tenant)
        for name in sorted(os.listdir(d)):
            if name.endswith(".meta"):
                with open(os.path.join(d, name)) as f:
                    out.append(FileMeta(**json.load(f)))
        return out


def validate_batch_input(data: bytes, max_requests: int = 50_000
                         ) -> tuple[list[dict], list[str]]:
    """Parse + validate a batch JSONL input; returns (requests, errors).

    Each line: {"custom_id": str, "method": "POST", "url": "/v1/...", "body": {...}}
    (the OpenAI Batch input contract the gateway fronts).
    """
    reqs: list[dict] = []
    errors: list[str] = []
    seen_ids: set[str] = set()
    for i, line in enumerate(data.decode("utf-8", "replace").splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            errors.append(f"line {i + 1}: invalid JSON")
            continue
        cid = obj.get("custom_id")
        if not isinstance(cid, str) or not cid:
            errors.append(f"line {i + 1}: missing custom_id")
            continue
        if cid in seen_ids:
            errors.append(f"line {i + 1}: duplicate custom_id {cid!r}")
            continue
        if obj.get("method", "POST") != "POST":
            errors.append(f"line {i + 1}: only POST supported")
            continue
        if not isinstance(obj.get("body"), dict):
            errors.append(f"line {i + 1}: missing body")
            continue
        url = obj.get("url", "")
        if url not in ("/v1/completions", "/v1/chat/completions", "/v1/embeddings"):
            errors.append(f"line {i + 1}: unsupported url {url!r}")
            continue
        if len(reqs) >= max_requests:
            errors.append(f"too many requests (max {max_requests})")
            break
        seen_ids.add(cid)
        reqs.append(obj)
    return reqs, errors
