"""Batch plane: OpenAI Batch Gateway + queue-driven Async Processor.

Parity: reference docs/architecture/advanced/batch/ (SURVEY §2.6 A3-A4).
"""

from llmd_tpu.batch.async_processor import (
    AsyncItem,
    AsyncProcessor,
    AsyncProcessorConfig,
    BudgetGate,
    ConstantGate,
    FileSpoolPuller,
    GATE_REGISTRY,
    MemoryQueuePuller,
    PrometheusBudgetGate,
    PrometheusSaturationGate,
)
from llmd_tpu.batch.files import FileStore, validate_batch_input
from llmd_tpu.batch.gateway import BatchGateway, BatchGatewayConfig
from llmd_tpu.batch.store import BatchRow, BatchStore

__all__ = [
    "AsyncItem", "AsyncProcessor", "AsyncProcessorConfig", "BatchGateway",
    "BatchGatewayConfig", "BatchRow", "BatchStore", "BudgetGate", "ConstantGate",
    "FileSpoolPuller", "FileStore", "GATE_REGISTRY", "MemoryQueuePuller",
    "PrometheusBudgetGate", "PrometheusSaturationGate", "validate_batch_input",
]
