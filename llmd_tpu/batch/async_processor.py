"""Async Processor — queue-driven dispatch into the router.

Parity: reference `llm-d-incubation/llm-d-async` as specified in
`docs/architecture/advanced/batch/async-processor.md:5-40` (SURVEY §2.6 A4):
- **Queue pullers** feed an internal work channel. The reference ships Redis
  sorted-set/pubsub and GCP Pub/Sub pullers; here the same `QueuePuller` seam has
  an in-memory priority puller and a file-spool puller (JSONL drop directory —
  the no-external-deps equivalent; Redis/PubSub implementations slot in behind
  the same interface).
- **Dispatch gates** decide when the next item may go out: `constant`
  (fixed concurrency), `budget` (token bucket — the `redis` budget gate's
  semantics), `prometheus-saturation` (poll a metrics endpoint, close the gate
  while a saturation metric is above threshold), `prometheus-budget` (spend a
  budget metric).
- **Workers** (default 8) POST to the router with deadline propagation and
  exponential backoff 2s -> 60s plus jitter on retryable failures
  (`async-processor.md:5-40`; values guides/asynchronous-processing/*).
"""

from __future__ import annotations

import asyncio
import heapq
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import aiohttp

DEADLINE_HEADER = "x-llm-d-deadline"  # absolute epoch seconds, propagated downstream


@dataclass
class AsyncItem:
    id: str
    url: str              # e.g. /v1/completions
    body: dict
    priority: int = 0
    deadline: Optional[float] = None   # epoch seconds
    attempts: int = 0


# ---------------------------------------------------------------- queue pullers


class QueuePuller:
    """Interface: await get() -> AsyncItem; ack/nack for redelivery semantics."""

    async def get(self) -> AsyncItem:  # pragma: no cover - interface
        raise NotImplementedError

    def nack(self, item: AsyncItem) -> None:
        raise NotImplementedError


class MemoryQueuePuller(QueuePuller):
    """In-process priority queue (the Redis sorted-set stand-in)."""

    def __init__(self) -> None:
        self._heap: list[tuple[int, float, int, AsyncItem]] = []
        self._cond = asyncio.Condition()
        self._seq = 0

    async def put(self, item: AsyncItem) -> None:
        async with self._cond:
            self._seq += 1
            heapq.heappush(self._heap, (-item.priority, time.monotonic(), self._seq, item))
            self._cond.notify()

    async def get(self) -> AsyncItem:
        async with self._cond:
            while not self._heap:
                await self._cond.wait()
            return heapq.heappop(self._heap)[3]

    def nack(self, item: AsyncItem) -> None:
        # nack is sync; on the event-loop thread the locked re-push rides the
        # wake-up task (heap mutation and notify both under the condition).
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # No running loop (sync caller): no task can be inside a critical
            # section, and the next put() will wake any waiters.
            # llmd-lint: allow[lock-unguarded-write] no running event loop in this branch, so nothing can hold the condition
            self._seq += 1
            # llmd-lint: allow[lock-unguarded-read] same single-threaded fallback path as the write above
            heapq.heappush(self._heap,
                           (-item.priority, time.monotonic(), self._seq, item))
            return
        loop.create_task(self._requeue(item))

    async def _requeue(self, item: AsyncItem) -> None:
        async with self._cond:
            self._seq += 1
            heapq.heappush(self._heap,
                           (-item.priority, time.monotonic(), self._seq, item))
            self._cond.notify()


class FileSpoolPuller(QueuePuller):
    """JSONL drop-directory puller: each *.json file is one queued item; claimed
    by rename (crash-safe: unclaimed files survive restarts)."""

    def __init__(self, spool_dir: str, poll_interval_s: float = 0.1) -> None:
        self.dir = spool_dir
        self.poll = poll_interval_s
        os.makedirs(spool_dir, exist_ok=True)

    async def get(self) -> AsyncItem:
        while True:
            for name in sorted(os.listdir(self.dir)):
                if not name.endswith(".json"):
                    continue
                path = os.path.join(self.dir, name)
                claimed = path + ".claimed"
                try:
                    os.rename(path, claimed)
                except OSError:
                    continue  # another worker got it
                try:
                    with open(claimed) as f:
                        d = json.load(f)
                    os.remove(claimed)
                    return AsyncItem(
                        id=d.get("id", name), url=d.get("url", "/v1/completions"),
                        body=d.get("body", {}), priority=int(d.get("priority", 0)),
                        deadline=d.get("deadline"),
                    )
                except (json.JSONDecodeError, OSError):
                    try:
                        os.remove(claimed)
                    except OSError:
                        pass
            await asyncio.sleep(self.poll)

    def nack(self, item: AsyncItem) -> None:
        path = os.path.join(self.dir, f"{item.id}.json")
        with open(path, "w") as f:
            json.dump({"id": item.id, "url": item.url, "body": item.body,
                       "priority": item.priority, "deadline": item.deadline}, f)


# ---------------------------------------------------------------- dispatch gates


class DispatchGate:
    """await acquire() blocks until one dispatch may proceed; release() on done."""

    async def acquire(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def release(self) -> None:
        pass


class ConstantGate(DispatchGate):
    """Fixed max in-flight dispatches."""

    def __init__(self, max_inflight: int = 8) -> None:
        self._sem = asyncio.Semaphore(max_inflight)

    async def acquire(self) -> None:
        await self._sem.acquire()

    def release(self) -> None:
        self._sem.release()


class BudgetGate(DispatchGate):
    """Token bucket: `rate` dispatches/second with burst `burst` (redis-budget
    gate semantics without the Redis)."""

    def __init__(self, rate: float, burst: float = 1.0) -> None:
        self.rate, self.burst = rate, max(1.0, burst)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = asyncio.Lock()

    async def acquire(self) -> None:
        while True:
            async with self._lock:
                now = time.monotonic()
                self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.rate
            await asyncio.sleep(wait)


class PrometheusSaturationGate(DispatchGate):
    """Polls a Prometheus text endpoint; the gate closes while `metric` exceeds
    `threshold` (async-processor.md prometheus-saturation gate)."""

    def __init__(self, metrics_url: str, metric: str, threshold: float,
                 poll_interval_s: float = 1.0, fail_open: bool = True) -> None:
        self.metrics_url = metrics_url
        self.metric = metric
        self.threshold = threshold
        self.poll = poll_interval_s
        self.fail_open = fail_open
        self.saturated = False
        self.last_value: Optional[float] = None
        self._session: Optional[aiohttp.ClientSession] = None

    def _get_session(self) -> aiohttp.ClientSession:
        # one shared connection pool for the metric polls — acquire() runs per
        # dispatched item, so a per-call session would mean TCP setup per request
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _poll_once(self) -> None:
        try:
            async with self._get_session().get(
                    self.metrics_url, timeout=aiohttp.ClientTimeout(total=2)) as resp:
                text = await resp.text()
            from llmd_tpu.core.metrics_contract import parse_prometheus

            val = next((v for name, _labels, v in parse_prometheus(text)
                        if name == self.metric), None)
            if val is not None:
                self.last_value = float(val)
                self.saturated = self.last_value > self.threshold
        except Exception:
            self.saturated = not self.fail_open

    async def acquire(self) -> None:
        await self._poll_once()
        while self.saturated:
            await asyncio.sleep(self.poll)
            await self._poll_once()


class PrometheusBudgetGate(PrometheusSaturationGate):
    """Like saturation, but spends a budget metric: dispatch allowed while the
    metric (e.g. spare capacity) is ABOVE threshold. With ``fail_open=False`` an
    unreachable metrics endpoint keeps the gate closed (a stale last_value from
    an earlier successful poll still counts as a reading)."""

    async def acquire(self) -> None:
        while True:
            await self._poll_once()
            if self.last_value is None:  # no reading ever obtained
                if self.fail_open:
                    return
            elif self.last_value > self.threshold:
                return
            await asyncio.sleep(self.poll)


GATE_REGISTRY: dict[str, Callable[..., DispatchGate]] = {
    "constant": ConstantGate,
    "budget": BudgetGate,
    "prometheus-saturation": PrometheusSaturationGate,
    "prometheus-budget": PrometheusBudgetGate,
}


# ---------------------------------------------------------------- the processor


@dataclass
class AsyncProcessorConfig:
    target_url: str = "http://127.0.0.1:8000"
    num_workers: int = 8
    max_attempts: int = 5
    backoff_base_s: float = 2.0    # reference: exp backoff 2s -> 60s + jitter
    backoff_max_s: float = 60.0
    request_timeout_s: float = 120.0


class AsyncProcessor:
    def __init__(self, cfg: AsyncProcessorConfig, puller: QueuePuller,
                 gate: Optional[DispatchGate] = None,
                 on_result: Optional[Callable[[AsyncItem, Optional[dict]], None]] = None,
                 ) -> None:
        self.cfg = cfg
        self.puller = puller
        self.gate = gate or ConstantGate(cfg.num_workers)
        self.on_result = on_result
        self._tasks: list[asyncio.Task] = []
        self._session: Optional[aiohttp.ClientSession] = None
        self.stats = {"dispatched": 0, "succeeded": 0, "failed": 0,
                      "retried": 0, "expired": 0}

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._worker(i))
                       for i in range(self.cfg.num_workers)]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        if self._session:
            await self._session.close()
        if hasattr(self.gate, "close"):
            await self.gate.close()

    def _backoff(self, attempt: int) -> float:
        base = min(self.cfg.backoff_max_s, self.cfg.backoff_base_s * (2 ** (attempt - 1)))
        return base + random.uniform(0, base * 0.25)  # jitter

    async def _worker(self, idx: int) -> None:
        while True:
            item = await self.puller.get()
            if item.deadline is not None and time.time() > item.deadline:
                self.stats["expired"] += 1
                self._finish(item, None)
                continue
            await self.gate.acquire()
            try:
                verdict, body = await self._dispatch(item)
            finally:
                self.gate.release()
            if verdict == "ok":
                self.stats["succeeded"] += 1
                self._finish(item, body)
                continue
            if verdict == "fatal":
                self.stats["failed"] += 1
                self._finish(item, None)
                continue
            item.attempts += 1
            if item.attempts >= self.cfg.max_attempts:
                self.stats["failed"] += 1
                self._finish(item, None)
                continue
            self.stats["retried"] += 1
            await asyncio.sleep(self._backoff(item.attempts))
            self.puller.nack(item)

    async def _dispatch(self, item: AsyncItem) -> tuple[str, Optional[dict]]:
        """Returns ("ok", body) | ("fatal", None) non-retryable | ("retry", None)."""
        headers = {}
        timeout = self.cfg.request_timeout_s
        if item.deadline is not None:
            headers[DEADLINE_HEADER] = str(item.deadline)  # deadline propagation
            timeout = max(0.1, min(timeout, item.deadline - time.time()))
        self.stats["dispatched"] += 1
        try:
            async with self._session.post(
                f"{self.cfg.target_url}{item.url}", json=item.body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status == 200:
                    return "ok", await resp.json(content_type=None)
                if resp.status in (400, 404, 413, 422):  # client errors: don't retry
                    return "fatal", None
                return "retry", None
        except asyncio.CancelledError:
            raise
        except Exception:
            return "retry", None

    def _finish(self, item: AsyncItem, result: Optional[dict]) -> None:
        if self.on_result is not None:
            try:
                self.on_result(item, result)
            except Exception:
                pass
