"""Batch metadata store (SQLite) — the gateway's PostgreSQL-equivalent.

Parity: reference `batch-gateway.md:11-87` — batch rows survive gateway crashes;
the processor's startup *recovery scan* re-queues every batch left in a
non-terminal state, so an interrupted run resumes instead of stranding
(`batch-gateway.md:55-59`). SQLite keeps the property (durable, transactional)
without an external database; the store API is the seam where PostgreSQL would
slot in.
"""

from __future__ import annotations

import json
import sqlite3
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

# OpenAI Batch lifecycle
NON_TERMINAL = ("validating", "in_progress", "finalizing", "cancelling")
TERMINAL = ("completed", "failed", "expired", "cancelled")


@dataclass
class BatchRow:
    id: str
    tenant: str
    input_file_id: str
    endpoint: str
    completion_window: str
    status: str = "validating"
    created_at: int = field(default_factory=lambda: int(time.time()))
    model: str = ""          # extracted at ingest for per-model worker routing
    priority: int = 0        # SLO priority (queue ordering)
    total: int = 0
    completed: int = 0
    failed: int = 0
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    errors: Optional[str] = None
    metadata: dict = field(default_factory=dict)

    def to_openai(self) -> dict:
        return {
            "id": self.id, "object": "batch", "endpoint": self.endpoint,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window, "status": self.status,
            "created_at": self.created_at,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "errors": json.loads(self.errors) if self.errors else None,
            "request_counts": {"total": self.total, "completed": self.completed,
                               "failed": self.failed},
            "metadata": self.metadata,
        }


class BatchStore:
    def __init__(self, path: str = ":memory:") -> None:
        self.db = sqlite3.connect(path, check_same_thread=False)
        self.db.execute(
            """CREATE TABLE IF NOT EXISTS batches (
                id TEXT PRIMARY KEY, tenant TEXT, input_file_id TEXT,
                endpoint TEXT, completion_window TEXT, status TEXT,
                created_at INTEGER, model TEXT, priority INTEGER,
                total INTEGER, completed INTEGER, failed INTEGER,
                output_file_id TEXT, error_file_id TEXT, errors TEXT,
                metadata TEXT)"""
        )
        self.db.commit()

    _COLS = ("id", "tenant", "input_file_id", "endpoint", "completion_window",
             "status", "created_at", "model", "priority", "total", "completed",
             "failed", "output_file_id", "error_file_id", "errors", "metadata")

    def create(self, tenant: str, input_file_id: str, endpoint: str,
               completion_window: str = "24h", metadata: Optional[dict] = None,
               priority: int = 0) -> BatchRow:
        row = BatchRow(
            id=f"batch_{uuid.uuid4().hex}", tenant=tenant,
            input_file_id=input_file_id, endpoint=endpoint,
            completion_window=completion_window, metadata=metadata or {},
            priority=priority,
        )
        self._write(row)
        return row

    def _write(self, row: BatchRow) -> None:
        vals = [getattr(row, c) for c in self._COLS]
        vals[-1] = json.dumps(row.metadata)
        self.db.execute(
            f"INSERT OR REPLACE INTO batches VALUES ({','.join('?' * len(self._COLS))})",
            vals,
        )
        self.db.commit()

    def update(self, row: BatchRow) -> None:
        self._write(row)

    def _from_row(self, r) -> BatchRow:
        d = dict(zip(self._COLS, r))
        d["metadata"] = json.loads(d["metadata"] or "{}")
        return BatchRow(**d)

    def get(self, batch_id: str, tenant: Optional[str] = None) -> Optional[BatchRow]:
        q = "SELECT * FROM batches WHERE id=?"
        args = [batch_id]
        if tenant is not None:  # tenant isolation at the metadata layer too
            q += " AND tenant=?"
            args.append(tenant)
        r = self.db.execute(q, args).fetchone()
        return self._from_row(r) if r else None

    def list(self, tenant: str, limit: int = 100) -> list[BatchRow]:
        rows = self.db.execute(
            "SELECT * FROM batches WHERE tenant=? ORDER BY created_at DESC LIMIT ?",
            (tenant, limit),
        ).fetchall()
        return [self._from_row(r) for r in rows]

    def recovery_scan(self) -> list[BatchRow]:
        """All non-terminal batches — re-queued by the processor at startup."""
        rows = self.db.execute(
            f"SELECT * FROM batches WHERE status IN ({','.join('?' * len(NON_TERMINAL))})",
            NON_TERMINAL,
        ).fetchall()
        return [self._from_row(r) for r in rows]

    def gc(self, older_than_s: float) -> int:
        """Delete terminal batches older than the retention window."""
        cutoff = int(time.time() - older_than_s)
        cur = self.db.execute(
            f"DELETE FROM batches WHERE status IN ({','.join('?' * len(TERMINAL))}) "
            "AND created_at < ?",
            (*TERMINAL, cutoff),
        )
        self.db.commit()
        return cur.rowcount
