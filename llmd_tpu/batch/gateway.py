"""Batch Gateway — OpenAI Files + Batches API over the router.

Parity: reference `llm-d/llm-d-batch-gateway` as specified in
`docs/architecture/advanced/batch/batch-gateway.md:11-87` (SURVEY §2.6 A3):
- REST surface: `/v1/files` (upload/fetch/content/delete) + `/v1/batches`
  (create/get/list/cancel), OpenAI Batch schema.
- Storage split: FS object store (S3 stand-in, tenant-hashed paths) +
  SQLite metadata (PostgreSQL stand-in) + in-process priority queue ordered by
  SLO priority (Redis sorted-set stand-in).
- Processor: poll → ingest (validate JSONL, count, extract model) → per-model
  workers bounded by global AND per-model concurrency caps → finalize (write
  output/error files, terminal status).
- Crash recovery: startup scan re-queues every non-terminal batch
  (`batch-gateway.md:55-59`).
- GC of aged terminal batches; tenant isolation via header + hashed paths;
  authN at the batch route (bearer key), authZ left to the inference path.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

import aiohttp
from aiohttp import web

from llmd_tpu.batch.files import FileStore, validate_batch_input
from llmd_tpu.batch.store import BatchRow, BatchStore

TENANT_HEADER = "x-llm-d-tenant"  # reference: tenant from auth header


def _window_seconds(window: str) -> float:
    try:
        if window.endswith("h"):
            return float(window[:-1]) * 3600
        if window.endswith("m"):
            return float(window[:-1]) * 60
        if window.endswith("s"):
            return float(window[:-1])
    except ValueError:
        pass
    return 24 * 3600


@dataclass
class BatchGatewayConfig:
    target_url: str = "http://127.0.0.1:8000"  # the llm-d Router
    files_root: str = "/tmp/llmd-batch-files"
    store_path: str = ":memory:"
    global_concurrency: int = 8     # cap across all models
    per_model_concurrency: int = 4  # cap per model
    poll_interval_s: float = 0.05
    gc_interval_s: float = 3600.0
    retention_s: float = 30 * 24 * 3600
    api_key: Optional[str] = None   # authN at the batch route; None = open
    request_timeout_s: float = 120.0


class BatchGateway:
    def __init__(self, cfg: BatchGatewayConfig, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.cfg = cfg
        self.host, self.port = host, port
        self.files = FileStore(cfg.files_root)
        self.store = BatchStore(cfg.store_path)
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._global_sem = asyncio.Semaphore(cfg.global_concurrency)
        self._model_sems: dict[str, asyncio.Semaphore] = {}
        self._cancel_requested: set[str] = set()
        self._tasks: list[asyncio.Task] = []
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self.stats = {"ingested": 0, "requests_done": 0, "requests_failed": 0,
                      "recovered": 0, "gc_deleted": 0}

    # ------------------------------------------------------------- lifecycle
    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        # crash recovery scan: everything non-terminal goes back on the queue
        for row in self.store.recovery_scan():
            self.stats["recovered"] += 1
            self._enqueue(row)
        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_post("/v1/files", self._upload_file)
        app.router.add_get("/v1/files/{file_id}", self._get_file)
        app.router.add_get("/v1/files/{file_id}/content", self._get_file_content)
        app.router.add_delete("/v1/files/{file_id}", self._delete_file)
        app.router.add_post("/v1/batches", self._create_batch)
        app.router.add_get("/v1/batches", self._list_batches)
        app.router.add_get("/v1/batches/{batch_id}", self._get_batch)
        app.router.add_post("/v1/batches/{batch_id}/cancel", self._cancel_batch)
        app.router.add_get("/health", lambda r: web.json_response({"status": "ok"}))
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._process_loop()),
                       loop.create_task(self._gc_loop())]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()

    # ------------------------------------------------------------- HTTP: auth
    def _tenant(self, request: web.Request) -> Optional[str]:
        if self.cfg.api_key is not None:
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {self.cfg.api_key}":
                return None
        return request.headers.get(TENANT_HEADER, "default")

    # ------------------------------------------------------------ HTTP: files
    async def _upload_file(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        filename, purpose, data = "file.jsonl", "batch", b""
        if request.content_type.startswith("multipart/"):
            async for part in await request.multipart():
                if part.name == "file":
                    filename = part.filename or filename
                    data = await part.read(decode=False)
                elif part.name == "purpose":
                    purpose = (await part.read(decode=False)).decode()
        else:
            data = await request.read()
            filename = request.query.get("filename", filename)
            purpose = request.query.get("purpose", purpose)
        if not data:
            return web.json_response({"error": "empty file"}, status=400)
        meta = self.files.put(tenant, filename, data, purpose)
        return web.json_response(meta.to_openai())

    async def _get_file(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        meta = self.files.get_meta(tenant, request.match_info["file_id"])
        if meta is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(meta.to_openai())

    async def _get_file_content(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        data = self.files.get_content(tenant, request.match_info["file_id"])
        if data is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(body=data, content_type="application/octet-stream")

    async def _delete_file(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        ok = self.files.delete(tenant, request.match_info["file_id"])
        return web.json_response({"deleted": ok,
                                  "id": request.match_info["file_id"]},
                                 status=200 if ok else 404)

    # ---------------------------------------------------------- HTTP: batches
    async def _create_batch(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        input_file_id = body.get("input_file_id", "")
        endpoint = body.get("endpoint", "/v1/completions")
        if self.files.get_meta(tenant, input_file_id) is None:
            return web.json_response({"error": "input file not found"}, status=404)
        row = self.store.create(
            tenant, input_file_id, endpoint,
            completion_window=body.get("completion_window", "24h"),
            metadata=body.get("metadata") or {},
            priority=int(body.get("priority", 0)),
        )
        self._enqueue(row)
        return web.json_response(row.to_openai())

    async def _get_batch(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        row = self.store.get(request.match_info["batch_id"], tenant)
        if row is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(row.to_openai())

    async def _list_batches(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        rows = self.store.list(tenant)
        return web.json_response({"object": "list",
                                  "data": [r.to_openai() for r in rows]})

    async def _cancel_batch(self, request: web.Request):
        tenant = self._tenant(request)
        if tenant is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        row = self.store.get(request.match_info["batch_id"], tenant)
        if row is None:
            return web.json_response({"error": "not found"}, status=404)
        if row.status in ("validating", "in_progress"):
            row.status = "cancelling"
            self.store.update(row)
            self._cancel_requested.add(row.id)
        return web.json_response(row.to_openai())

    # -------------------------------------------------------------- processor
    def _enqueue(self, row: BatchRow) -> None:
        # SLO-priority sorted set: higher priority first, FIFO within a level
        self._queue.put_nowait((-row.priority, row.created_at, row.id))

    def _model_sem(self, model: str) -> asyncio.Semaphore:
        if model not in self._model_sems:
            self._model_sems[model] = asyncio.Semaphore(self.cfg.per_model_concurrency)
        return self._model_sems[model]

    async def _process_loop(self) -> None:
        running: set[asyncio.Task] = set()
        while True:
            _, _, batch_id = await self._queue.get()
            row = self.store.get(batch_id)
            if row is None:
                continue
            if row.status == "cancelling":
                # covers both live cancels and 'cancelling' rows found by the
                # recovery scan (the in-memory cancel set dies with the process)
                row.status = "cancelled"
                self.store.update(row)
                self._cancel_requested.discard(row.id)
                continue
            # 'finalizing' re-runs after a crash mid-finalize (recovery scan);
            # _run_batch resets counts so the re-run can't double-count
            if row.status not in ("validating", "in_progress", "finalizing"):
                continue
            t = asyncio.get_running_loop().create_task(self._run_batch_safe(row))
            running.add(t)
            t.add_done_callback(running.discard)

    async def _run_batch_safe(self, row: BatchRow) -> None:
        """A crashed batch run must still reach a terminal status — an exception
        swallowed by the fire-and-forget task would strand it non-terminal with
        clients polling forever."""
        try:
            await self._run_batch(row)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            try:
                row.status = "failed"
                row.errors = json.dumps(
                    [{"message": f"processor error: {type(exc).__name__}: {exc}"}])
                self.store.update(row)
            except Exception:
                pass  # metadata store down too: recovery scan re-runs it on restart

    async def _run_batch(self, row: BatchRow) -> None:
        data = self.files.get_content(row.tenant, row.input_file_id)
        if data is None:
            row.status, row.errors = "failed", json.dumps(
                [{"message": "input file disappeared"}])
            self.store.update(row)
            return
        reqs, errors = validate_batch_input(data)
        if errors:
            # surfaced even when some lines are valid (lenient ingest: valid
            # lines run, rejects are recorded on the batch object)
            row.errors = json.dumps([{"message": e} for e in errors[:100]])
        if not reqs:
            row.status = "failed"
            self.store.update(row)
            return
        row.total = len(reqs)
        row.completed = row.failed = 0  # reset: recovery may re-run this batch
        row.model = next((r["body"].get("model", "") for r in reqs), "")
        row.status = "in_progress"
        self.store.update(row)
        self.stats["ingested"] += 1

        deadline = row.created_at + _window_seconds(row.completion_window)
        results: list[Optional[dict]] = [None] * len(reqs)
        cancelled = False

        async def one(i: int, req: dict) -> None:
            nonlocal cancelled
            model = req["body"].get("model", row.model)
            # Per-model cap OUTSIDE the global cap: a hot model's excess requests
            # queue at their own semaphore without holding global slots, so other
            # models' traffic is never starved by one model's backlog.
            async with self._model_sem(model), self._global_sem:
                # cancellation/expiry checked under the semaphore — every queued
                # request re-evaluates right before its dispatch slot
                if cancelled or row.id in self._cancel_requested:
                    cancelled = True
                    return
                if time.time() > deadline:
                    results[i] = {"error": {"message": "completion window expired"}}
                    return
                results[i] = await self._dispatch(row, req)

        # per-model workers: bounded fan-out under both caps
        await asyncio.gather(*(one(i, r) for i, r in enumerate(reqs)))

        if cancelled:
            row.status = "cancelled"
            self._cancel_requested.discard(row.id)
            self.store.update(row)
            return
        await self._finalize(row, reqs, results)

    async def _dispatch(self, row: BatchRow, req: dict) -> dict:
        url = f"{self.cfg.target_url}{req.get('url', row.endpoint)}"
        try:
            async with self._session.post(
                url, json=req["body"],
                headers={TENANT_HEADER: row.tenant,
                         "x-llm-d-inference-objective": "batch"},
                timeout=aiohttp.ClientTimeout(total=self.cfg.request_timeout_s),
            ) as resp:
                body = await resp.json(content_type=None)
                if resp.status == 200:
                    self.stats["requests_done"] += 1
                    return {"status_code": 200, "body": body}
                self.stats["requests_failed"] += 1
                return {"status_code": resp.status, "body": body,
                        "error": {"message": f"HTTP {resp.status}"}}
        except Exception as exc:
            self.stats["requests_failed"] += 1
            return {"error": {"message": f"{type(exc).__name__}: {exc}"}}

    async def _finalize(self, row: BatchRow, reqs: list[dict],
                        results: list[Optional[dict]]) -> None:
        row.status = "finalizing"
        self.store.update(row)
        out_lines, err_lines = [], []
        for req, res in zip(reqs, results):
            res = res or {"error": {"message": "not executed"}}
            line = {"id": f"batch_req_{uuid.uuid4().hex[:16]}",
                    "custom_id": req["custom_id"],
                    "response": ({"status_code": res["status_code"],
                                  "body": res["body"]}
                                 if "status_code" in res else None),
                    "error": res.get("error")}
            if res.get("status_code") == 200:
                row.completed += 1
                out_lines.append(line)
            else:
                row.failed += 1
                err_lines.append(line)
        if out_lines:
            meta = self.files.put(
                row.tenant, f"{row.id}_output.jsonl",
                "\n".join(json.dumps(l) for l in out_lines).encode(),
                purpose="batch_output")
            row.output_file_id = meta.id
        if err_lines:
            meta = self.files.put(
                row.tenant, f"{row.id}_errors.jsonl",
                "\n".join(json.dumps(l) for l in err_lines).encode(),
                purpose="batch_output")
            row.error_file_id = meta.id
        row.status = "completed" if row.completed or not row.failed else "failed"
        self.store.update(row)

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.gc_interval_s)
            self.stats["gc_deleted"] += self.store.gc(self.cfg.retention_s)
