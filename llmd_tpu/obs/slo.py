"""SLO objectives, rolling attainment, and multi-window burn rates.

The router declares latency objectives via environment (``LLMD_SLO_TTFT_MS``,
``LLMD_SLO_E2E_MS``, ``LLMD_SLO_TARGET``) with optional per-tenant overrides
(``LLMD_SLO_TENANT_OVERRIDES``, e.g.
``gold:ttft_ms=200,e2e_ms=2000,target=0.999;bronze:e2e_ms=10000``), then feeds
every request's TTFT/e2e into this engine. The engine keeps minute-bucketed
good/total counts per (tenant, objective) and answers, at scrape time:

* **attainment** — fraction of requests meeting the objective over a rolling
  window (5m and 1h), and
* **burn rate** — ``(1 - attainment) / (1 - target)``: how many times faster
  than "exactly at target" the error budget is being spent. 1.0 means the
  budget lasts precisely its period; 14.4 over 5m is the classic page-now
  threshold (see observability/slo-attribution.md).

Memory is bounded: each (tenant, objective) series holds at most
``window_minutes + 1`` minute buckets, and tenants idle past the long window
are pruned — so a tenant-label cardinality attack costs O(active tenants),
not O(all tenants ever seen).

Clock is injectable (``now_fn``) so window-boundary math is unit-testable.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["SLOConfig", "SLOEngine", "WINDOWS_S"]

# Rolling windows exposed as gauge label values: (label, seconds).
WINDOWS_S: Tuple[Tuple[str, int], ...] = (("5m", 300), ("1h", 3600))

_OBJECTIVE_KEYS = {"ttft_ms": "ttft", "e2e_ms": "e2e"}


class SLOConfig:
    """Per-tenant objective thresholds (ms) and attainment target."""

    __slots__ = ("ttft_ms", "e2e_ms", "target")

    def __init__(self, ttft_ms: float = 0.0, e2e_ms: float = 0.0,
                 target: float = 0.99):
        self.ttft_ms = float(ttft_ms)
        self.e2e_ms = float(e2e_ms)
        # target is the attainment objective (0 < target < 1); clamp so the
        # burn-rate denominator (1 - target) stays sane
        self.target = min(0.9999, max(0.5, float(target)))

    def threshold_ms(self, objective: str) -> float:
        return self.ttft_ms if objective == "ttft" else self.e2e_ms

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"SLOConfig(ttft_ms={self.ttft_ms}, e2e_ms={self.e2e_ms}, "
                f"target={self.target})")


def _parse_overrides(spec: str, base: SLOConfig) -> Dict[str, SLOConfig]:
    """``tenant:key=val,key=val;tenant2:...`` → per-tenant configs layered
    over the defaults. Malformed entries are skipped, never fatal."""
    out: Dict[str, SLOConfig] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry or ":" not in entry:
            continue
        tenant, _, kvs = entry.partition(":")
        tenant = tenant.strip()
        if not tenant:
            continue
        cfg = SLOConfig(base.ttft_ms, base.e2e_ms, base.target)
        for kv in kvs.split(","):
            k, _, v = kv.partition("=")
            k = k.strip()
            try:
                val = float(v)
            except (TypeError, ValueError):
                continue
            if k in ("ttft_ms", "e2e_ms", "target"):
                setattr(cfg, k, val if k != "target"
                        else min(0.9999, max(0.5, val)))
        out[tenant] = cfg
    return out


class _Series:
    """Minute-bucketed good/total counts for one (tenant, objective)."""

    __slots__ = ("buckets",)

    def __init__(self):
        # deque of [minute_epoch, good, total]; newest last
        self.buckets: deque = deque()

    def add(self, minute: int, good: bool) -> None:
        if self.buckets and self.buckets[-1][0] == minute:
            b = self.buckets[-1]
        else:
            b = [minute, 0, 0]
            self.buckets.append(b)
            # bound: longest window + the in-progress minute
            max_keep = WINDOWS_S[-1][1] // 60 + 1
            while len(self.buckets) > max_keep:
                self.buckets.popleft()
        b[1] += 1 if good else 0
        b[2] += 1

    def counts(self, now_minute: int, window_minutes: int) -> Tuple[int, int]:
        """(good, total) over [now_minute - window_minutes + 1, now_minute]:
        the in-progress minute counts toward its window."""
        lo = now_minute - window_minutes + 1
        good = total = 0
        for minute, g, t in self.buckets:
            if minute >= lo:
                good += g
                total += t
        return good, total

    def newest_minute(self) -> int:
        return self.buckets[-1][0] if self.buckets else 0


class SLOEngine:
    """Feed per-request latencies in; read attainment/burn gauges out.

    Single-threaded by construction on the router (asyncio loop observes,
    aiohttp scrape handler reads on the same loop) — no lock needed; the
    engine never blocks."""

    def __init__(self, default: Optional[SLOConfig] = None,
                 overrides: Optional[Dict[str, SLOConfig]] = None,
                 now_fn: Callable[[], float] = time.time):
        self.default = default or SLOConfig()
        self.overrides = dict(overrides or {})
        self.now_fn = now_fn
        self._series: Dict[Tuple[str, str], _Series] = {}
        self.breach_counter = None  # optional: llm_d_epp_slo_breaches_total

    @classmethod
    def from_env(cls, environ=os.environ,
                 now_fn: Callable[[], float] = time.time) -> "SLOEngine":
        base = SLOConfig(
            ttft_ms=float(environ.get("LLMD_SLO_TTFT_MS", "0") or 0),
            e2e_ms=float(environ.get("LLMD_SLO_E2E_MS", "0") or 0),
            target=float(environ.get("LLMD_SLO_TARGET", "0.99") or 0.99),
        )
        overrides = _parse_overrides(
            environ.get("LLMD_SLO_TENANT_OVERRIDES", ""), base)
        return cls(default=base, overrides=overrides, now_fn=now_fn)

    @property
    def enabled(self) -> bool:
        if self.default.ttft_ms > 0 or self.default.e2e_ms > 0:
            return True
        return any(c.ttft_ms > 0 or c.e2e_ms > 0
                   for c in self.overrides.values())

    def config_for(self, tenant: str) -> SLOConfig:
        return self.overrides.get(tenant, self.default)

    # --------------------------------------------------------------- feeding
    def observe(self, tenant: str, objective: str,
                latency_s: float) -> bool:
        """Record one request's latency against an objective ('ttft'|'e2e').
        Returns True when the request BREACHED (caller emits the flight
        event); objectives with no threshold configured are ignored."""
        cfg = self.config_for(tenant)
        threshold_ms = cfg.threshold_ms(objective)
        if threshold_ms <= 0:
            return False
        good = latency_s * 1e3 <= threshold_ms
        minute = int(self.now_fn() // 60)
        key = (tenant, objective)
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = _Series()
        series.add(minute, good)
        if not good and self.breach_counter is not None:
            self.breach_counter.labels(tenant=tenant,
                                       objective=objective).inc()
        return not good

    # --------------------------------------------------------------- reading
    def attainment(self, tenant: str, objective: str,
                   window_s: int) -> Optional[float]:
        series = self._series.get((tenant, objective))
        if series is None:
            return None
        now_minute = int(self.now_fn() // 60)
        good, total = series.counts(now_minute, max(1, window_s // 60))
        if total == 0:
            return None
        return good / total

    def burn_rate(self, tenant: str, objective: str,
                  window_s: int) -> Optional[float]:
        att = self.attainment(tenant, objective, window_s)
        if att is None:
            return None
        cfg = self.config_for(tenant)
        return (1.0 - att) / (1.0 - cfg.target)

    def gauge_samples(self, kind: str) -> List[Tuple[Dict[str, str], float]]:
        """Scrape-time callback body for set_labels_function:
        kind='attainment' or 'burn'. Prunes tenants idle past the long
        window so gauge cardinality tracks *active* tenants."""
        now_minute = int(self.now_fn() // 60)
        horizon = now_minute - (WINDOWS_S[-1][1] // 60 + 1)
        dead = [k for k, s in self._series.items()
                if s.newest_minute() < horizon]
        for k in dead:
            del self._series[k]
        out: List[Tuple[Dict[str, str], float]] = []
        for (tenant, objective) in self._series:
            for label, window_s in WINDOWS_S:
                v = (self.attainment(tenant, objective, window_s)
                     if kind == "attainment"
                     else self.burn_rate(tenant, objective, window_s))
                if v is None:
                    continue
                out.append(({"tenant": tenant, "objective": objective,
                             "window": label}, round(v, 6)))
        return out
