"""Fleet rollup plane: one router scrape answers fleet health.

``FleetRollup`` rides the router's ``MetricsPoller`` extractor chain
(duck-typed to the datalayer ``Extractor`` interface — ``name`` +
``extract(ep, raw)`` — so ``obs/`` stays free of router imports). Every
per-replica scrape updates that replica's cached sample in O(one pass over
its raw samples); the aggregate ``llmd_tpu:fleet_*`` gauges are computed at
router scrape time over the cached samples — no second fan-out, no
re-scraping, and the pool controller reads the same rollup instead of
re-summing per-replica attributes itself.

Boundedness under churn: state is one fixed-size ``_ReplicaSample`` per
*live* endpoint; ``forget(address)`` (cascaded from ``MetricsPoller.forget``
when discovery drops a replica) deletes it, so 100 replicas cycling through
the pool leave exactly the live set behind.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

__all__ = ["FleetRollup"]

_DECODE_TOKENS = "llmd_tpu:decode_tokens_total"
_RUNNING = "vllm:num_requests_running"
_WAITING = "vllm:num_requests_waiting"
_KV_USAGE = "vllm:kv_cache_usage_perc"
_HBM_USE = "llmd_tpu:device_hbm_bytes_in_use"
_HBM_LIMIT = "llmd_tpu:device_hbm_limit_bytes"
_FABRIC = "llmd_tpu:device_fabric_alive"
_STALLED = "llmd_tpu:engine_stalled"
_GOODPUT = "llmd_tpu:goodput_tokens_total"
_MFU = "llmd_tpu:program_mfu"


class _ReplicaSample:
    """Last-scrape rollup inputs for one replica. Fixed size by design."""

    __slots__ = ("t_mono", "tokens", "tok_per_s", "running", "waiting",
                 "kv_usage", "hbm_headroom", "fabric_alive", "stalled",
                 "gp_committed", "gp_all", "gp_committed_delta",
                 "gp_all_delta", "mfu_mean")

    def __init__(self):
        self.t_mono: Optional[float] = None
        self.tokens: Optional[float] = None
        self.tok_per_s = 0.0
        self.running = 0.0
        self.waiting = 0.0
        self.kv_usage: Optional[float] = None
        self.hbm_headroom: Optional[float] = None
        self.fabric_alive = True
        self.stalled = False
        # utilization plane: cumulative goodput counters (for deltas) and
        # the replica's mean per-program MFU sample (None off-device)
        self.gp_committed: Optional[float] = None
        self.gp_all: Optional[float] = None
        self.gp_committed_delta = 0.0
        self.gp_all_delta = 0.0
        self.mfu_mean: Optional[float] = None


class FleetRollup:
    """MetricsPoller extractor aggregating per-replica scrapes."""

    name = "fleet-rollup"

    def __init__(self, now_fn: Callable[[], float] = time.monotonic):
        self.now_fn = now_fn
        self._replicas: Dict[str, _ReplicaSample] = {}

    # ------------------------------------------------------------ extraction
    def extract(self, ep, raw: list) -> None:
        """One pass over a replica's parsed /metrics samples."""
        s = self._replicas.get(ep.address)
        if s is None:
            s = self._replicas[ep.address] = _ReplicaSample()
        tokens = None
        hbm_use: Dict[str, float] = {}
        hbm_limit: Dict[str, float] = {}
        kv = None
        fabric: Optional[float] = None
        stalled: Optional[float] = None
        running = waiting = 0.0
        gp_committed = gp_all = None
        mfu_samples: list = []
        for name, labels, value in raw:
            if name == _DECODE_TOKENS:
                tokens = value
            elif name == _RUNNING:
                running = value
            elif name == _WAITING:
                waiting = value
            elif name == _KV_USAGE:
                kv = value
            elif name == _HBM_USE:
                hbm_use[labels.get("device", "")] = value
            elif name == _HBM_LIMIT:
                hbm_limit[labels.get("device", "")] = value
            elif name == _FABRIC:
                fabric = value
            elif name == _STALLED:
                stalled = value
            elif name == _GOODPUT:
                gp_all = (gp_all or 0.0) + value
                if labels.get("kind") == "committed":
                    gp_committed = (gp_committed or 0.0) + value
            elif name == _MFU:
                mfu_samples.append(value)
        now = self.now_fn()
        if tokens is not None and s.tokens is not None and s.t_mono is not None:
            dt = now - s.t_mono
            delta = tokens - s.tokens
            # counter reset (replica restart) → re-baseline, don't go negative
            s.tok_per_s = delta / dt if dt > 0 and delta >= 0 else 0.0
        # goodput ratio comes from scrape-to-scrape counter deltas (same
        # reset discipline as tok_per_s: negative delta = replica restart)
        if gp_all is not None and s.gp_all is not None:
            d_all = gp_all - s.gp_all
            d_com = (gp_committed or 0.0) - (s.gp_committed or 0.0)
            if d_all >= 0 and d_com >= 0:
                s.gp_all_delta, s.gp_committed_delta = d_all, d_com
            else:
                s.gp_all_delta = s.gp_committed_delta = 0.0
        s.gp_all = gp_all
        s.gp_committed = gp_committed
        s.mfu_mean = (sum(mfu_samples) / len(mfu_samples)
                      if mfu_samples else None)
        s.t_mono = now
        s.tokens = tokens
        s.running = running
        s.waiting = waiting
        s.kv_usage = kv
        headroom = sum(limit - hbm_use.get(dev, 0.0)
                       for dev, limit in hbm_limit.items())
        s.hbm_headroom = headroom if hbm_limit else None
        # device-plane gauges are absent on backends without them (CPU):
        # absent means "no evidence of trouble", not dead/stalled
        s.fabric_alive = fabric != 0.0 if fabric is not None else True
        s.stalled = stalled == 1.0 if stalled is not None else False

    def forget(self, address: str) -> None:
        self._replicas.pop(address, None)

    # -------------------------------------------------------------- rollups
    def __len__(self) -> int:
        return len(self._replicas)

    def snapshot(self) -> dict:
        """Aggregate over cached replica samples (router scrape time)."""
        reps = list(self._replicas.values())
        headrooms = [s.hbm_headroom for s in reps if s.hbm_headroom is not None]
        kvs = [s.kv_usage for s in reps if s.kv_usage is not None]
        mfus = [s.mfu_mean for s in reps if s.mfu_mean is not None]
        gp_all = sum(s.gp_all_delta for s in reps)
        gp_com = sum(s.gp_committed_delta for s in reps)
        return {
            "replicas": len(reps),
            "tokens_per_second": sum(s.tok_per_s for s in reps),
            "running": sum(s.running for s in reps),
            "waiting": sum(s.waiting for s in reps),
            "hbm_headroom_min": min(headrooms) if headrooms else 0.0,
            "hbm_headroom_total": sum(headrooms) if headrooms else 0.0,
            "kv_utilization_mean": sum(kvs) / len(kvs) if kvs else 0.0,
            "fabric_alive": sum(1 for s in reps if s.fabric_alive),
            "stalled": sum(1 for s in reps if s.stalled),
            # token-weighted fleet goodput over the last scrape interval
            "goodput_committed_ratio": gp_com / gp_all if gp_all > 0 else 0.0,
            "mfu_mean": sum(mfus) / len(mfus) if mfus else 0.0,
        }

    def running_total(self) -> float:
        """Pool-controller consumption path (in-flight fleet-wide)."""
        return sum(s.running for s in self._replicas.values())

    def waiting_total(self) -> float:
        return sum(s.waiting for s in self._replicas.values())

    def bind_gauges(self, rm) -> None:
        """Point the RouterMetrics fleet gauges at this rollup (scrape-time
        callbacks — the gauges always expose the freshest aggregate)."""
        rm.fleet_replicas.set_function(lambda: len(self._replicas))
        rm.fleet_tokens_per_second.set_function(
            lambda: self.snapshot()["tokens_per_second"])
        rm.fleet_running.set_function(self.running_total)
        rm.fleet_waiting.set_function(self.waiting_total)
        rm.fleet_hbm_headroom_min.set_function(
            lambda: self.snapshot()["hbm_headroom_min"])
        rm.fleet_hbm_headroom_total.set_function(
            lambda: self.snapshot()["hbm_headroom_total"])
        rm.fleet_kv_utilization.set_function(
            lambda: self.snapshot()["kv_utilization_mean"])
        rm.fleet_fabric_alive.set_function(
            lambda: self.snapshot()["fabric_alive"])
        rm.fleet_stalled.set_function(lambda: self.snapshot()["stalled"])
        rm.fleet_goodput_ratio.set_function(
            lambda: self.snapshot()["goodput_committed_ratio"])
        rm.fleet_mfu.set_function(lambda: self.snapshot()["mfu_mean"])
