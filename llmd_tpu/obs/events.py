"""Per-request flight recorder: bounded, always-on lifecycle timelines.

Traces sample (prod default 0.1 — tracing.py), so the tail request an
operator needs to debug is usually the one that wasn't sampled. The flight
recorder is the missing middle layer between aggregate metrics and sampled
spans: every request gets a structured event timeline (arrival, routing
decision, flow-control queueing, admission, prefill/decode progress,
preemption, KV offload/reload, retirement) held in a lock-protected ring
buffer with hard memory bounds, queryable live via ``/debug/requests`` on
both servers.

Bounds (env knobs, deploy/ENV_VARS.md):

* ``LLMD_FLIGHT_MAX_REQUESTS`` — ring capacity; oldest non-retained record
  evicted past it.
* ``LLMD_FLIGHT_MAX_EVENTS`` — per-request event cap; excess events are
  counted in ``events_dropped`` (terminal events always land).
* ``LLMD_FLIGHT_SLO_MS`` — tail capture: a request finishing slower than
  this is force-retained past ring eviction AND force-sampled into the
  tracer (a ``flight.slo_breach`` span carrying the timeline exports even
  when the sampler said no), so the slow tail is always debuggable.
* ``LLMD_FLIGHT_TAIL_KEEP`` — cap on force-retained records.

Threading: engine events come from the engine step-loop thread, router
events from the asyncio loop, and ``/debug`` reads from aiohttp handlers —
every mutation and snapshot takes the recorder lock (same discipline as
the metrics registry).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

__all__ = ["EVENT_CATALOG", "FlightRecorder", "RequestRecord",
           "debug_list_response", "debug_detail_response"]

# The authoritative event-name catalog. observability/flight-recorder.md
# documents each; tools/lint_events.py cross-checks emit sites against BOTH
# in CI, so a renamed or undocumented event fails the gate.
EVENT_CATALOG = (
    # router plane
    "arrival",
    "flow_enqueue",
    "flow_dispatch",
    "flow_reject",
    "routing_decision",
    "route_decision",
    "kv_pull_stamped",
    "forward",
    "response",
    "rejected",
    "error",
    # router resilience plane (router/resilience.py + server retry loop)
    "deadline_exceeded",
    "retry",
    "hedge",
    "breaker_open",
    "breaker_close",
    "slo_breach",
    # engine plane
    "admitted",
    "prefill_start",
    "prefill_end",
    "first_token",
    "decode",
    "chain_dispatch",
    "chain_retire",
    "spec_draft",
    "spec_verify",
    "structured_compile",
    "structured_mask",
    "preempted",
    "kv_reload",
    "kv_offload",
    "kv_pull",
    "kv_flush",
    "kv_durable_get",
    "retired",
    "aborted",
    "drain_start",
    "drain_done",
    # pool plane (pool/controller.py replica lifecycle; system events —
    # replica churn has no owning request)
    "pool_scale_up",
    "pool_scale_down",
    "pool_warm_start",
    # device plane (obs/device.py DeviceMonitor; system events — a hung TPU
    # or wedged fabric has no owning request either)
    "engine_stalled",
    "engine_recovered",
    "fabric_dead",
    "fabric_recovered",
    "profile_capture",
)

_TERMINAL_STATUS = {"finished", "aborted", "rejected", "error"}


class RequestRecord:
    """One request's timeline. Mutated only under the recorder lock."""

    __slots__ = ("request_id", "model", "trace_id", "tenant", "status",
                 "t0_mono", "t0_wall", "events", "events_dropped",
                 "finish_reason", "e2e_s", "retained")

    def __init__(self, request_id: str, model: str, trace_id: str,
                 tenant: str = "") -> None:
        self.request_id = request_id
        self.model = model
        self.trace_id = trace_id
        self.tenant = tenant
        self.status = "active"
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()
        self.events: List[dict] = []
        self.events_dropped = 0
        self.finish_reason: Optional[str] = None
        self.e2e_s: Optional[float] = None
        self.retained = False

    def latency_s(self) -> float:
        """Final e2e for finished records, age-so-far for active ones."""
        if self.e2e_s is not None:
            return self.e2e_s
        return time.monotonic() - self.t0_mono

    def summary(self) -> dict:
        return {
            "request_id": self.request_id,
            "model": self.model,
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "status": self.status,
            "start_unix": round(self.t0_wall, 3),
            "latency_ms": round(self.latency_s() * 1e3, 3),
            "finish_reason": self.finish_reason,
            "n_events": len(self.events),
            "events_dropped": self.events_dropped,
            "retained": self.retained,
        }

    def to_dict(self) -> dict:
        d = self.summary()
        d["events"] = list(self.events)
        return d


class FlightRecorder:
    """Lock-protected ring buffer of per-request event timelines."""

    def __init__(self, max_requests: int = 512, max_events: int = 256,
                 slo_ms: float = 0.0, tail_keep: int = 64,
                 tracer=None) -> None:
        self.max_requests = max(1, int(max_requests))
        self.max_events = max(1, int(max_events))
        self.slo_ms = float(slo_ms)
        self.tail_keep = max(0, int(tail_keep))
        self.tracer = tracer
        # Owner-set retire hook: called with the finished record's to_dict()
        # AFTER the lock is released (the attribution exporter hangs here).
        self.on_finish = None
        self._lock = threading.Lock()
        self._records: "OrderedDict[str, RequestRecord]" = OrderedDict()
        # non-request-scoped events (offload-tier demotions etc.)
        self._system: deque = deque(maxlen=256)

    @classmethod
    def from_env(cls, tracer=None) -> "FlightRecorder":
        return cls(
            max_requests=int(os.environ.get("LLMD_FLIGHT_MAX_REQUESTS", "512")),
            max_events=int(os.environ.get("LLMD_FLIGHT_MAX_EVENTS", "256")),
            slo_ms=float(os.environ.get("LLMD_FLIGHT_SLO_MS", "0")),
            tail_keep=int(os.environ.get("LLMD_FLIGHT_TAIL_KEEP", "64")),
            tracer=tracer,
        )

    # ------------------------------------------------------------- recording
    def start(self, request_id: str, model: str = "",
              trace_id: str = "", tenant: str = "") -> None:
        """Open a record (idempotent: a re-start keeps the existing timeline
        but backfills model/trace/tenant if the first opener didn't know
        them)."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is not None:
                rec.model = rec.model or model
                rec.trace_id = rec.trace_id or trace_id
                rec.tenant = rec.tenant or tenant
                return
            self._records[request_id] = RequestRecord(request_id, model,
                                                      trace_id, tenant)
            self._evict_locked()

    def record(self, request_id: str, event: str, **attrs: Any) -> None:
        """Append one timestamped event; unknown request ids are a no-op (the
        emitter must never crash the step loop over a missed start)."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                return
            self._append_locked(rec, event, attrs, force=False)

    def record_system(self, event: str, **attrs: Any) -> None:
        """Events with no owning request (batch offload demotions)."""
        entry = {"event": event, "t_unix": round(time.time(), 3)}
        entry.update(attrs)
        with self._lock:
            self._system.append(entry)

    def finish(self, request_id: str, event: str = "retired",
               status: str = "finished", **attrs: Any) -> None:
        """Terminal transition: records ``event`` (bypassing the per-request
        cap), stamps e2e latency, and applies SLO tail capture."""
        breach: Optional[RequestRecord] = None
        finished: Optional[dict] = None
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None or rec.status in _TERMINAL_STATUS:
                return
            rec.status = status if status in _TERMINAL_STATUS else "finished"
            rec.e2e_s = time.monotonic() - rec.t0_mono
            rec.finish_reason = str(attrs.get("reason", "")) or rec.finish_reason
            self._append_locked(rec, event, attrs, force=True)
            if self.slo_ms > 0 and rec.e2e_s * 1e3 >= self.slo_ms:
                rec.retained = True
                self._trim_tail_locked()
                breach = rec
            if self.on_finish is not None:
                finished = rec.to_dict()
        if breach is not None:
            self._force_trace(breach)
        if finished is not None:
            try:
                self.on_finish(finished)
            except Exception:
                pass  # exporters must never take down retirement

    # --------------------------------------------------------------- queries
    def get(self, request_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._records.get(request_id)
            return rec.to_dict() if rec is not None else None

    def snapshot(self, status: Optional[str] = None,
                 model: Optional[str] = None,
                 min_latency_ms: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 limit: int = 100) -> List[dict]:
        """Newest-first summaries, filtered by status/model/min-latency/
        trace id (the trace filter is how a sampled span is correlated back
        to its full flight timeline — see tools/dump_flight.py --trace)."""
        with self._lock:
            recs = list(self._records.values())
        out = []
        for rec in reversed(recs):
            if status and rec.status != status:
                continue
            if model and rec.model != model:
                continue
            if trace_id and rec.trace_id != trace_id:
                continue
            if min_latency_ms is not None and rec.latency_s() * 1e3 < min_latency_ms:
                continue
            out.append(rec.summary())
            if len(out) >= max(1, limit):
                break
        return out

    def system_events(self) -> List[dict]:
        with self._lock:
            return list(self._system)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # ------------------------------------------------------------- internals
    def _append_locked(self, rec: RequestRecord, event: str, attrs: dict,
                       force: bool) -> None:
        if not force and len(rec.events) >= self.max_events:
            rec.events_dropped += 1
            return
        entry: Dict[str, Any] = {
            "event": event,
            "t_ms": round((time.monotonic() - rec.t0_mono) * 1e3, 3),
        }
        for k, v in attrs.items():
            if v is not None:
                entry[k] = v
        rec.events.append(entry)

    def _evict_locked(self) -> None:
        """Ring semantics: drop the oldest non-retained record. Tail-captured
        records survive eviction (that's the point of tail capture); if
        somehow everything is retained, the oldest goes anyway — the memory
        bound is hard."""
        while len(self._records) > self.max_requests:
            victim = next(
                (rid for rid, r in self._records.items() if not r.retained),
                None,
            )
            if victim is None:
                self._records.popitem(last=False)
            else:
                del self._records[victim]

    def _trim_tail_locked(self) -> None:
        retained = [rid for rid, r in self._records.items() if r.retained]
        while len(retained) > self.tail_keep:
            del self._records[retained.pop(0)]

    def _force_trace(self, rec: RequestRecord) -> None:
        """Force-sample an SLO breach into the tracer: export a synthetic
        ``flight.slo_breach`` span carrying the timeline even when the
        head-based sampler dropped the trace — Grafana's exemplar jump then
        always lands on a trace for the slow tail."""
        tracer = self.tracer
        if tracer is None or not getattr(tracer.cfg, "enabled", False):
            return
        try:
            from llmd_tpu.obs.tracing import Span, SpanContext, _rand_hex

            trace_id = rec.trace_id or _rand_hex(16)
            span = Span(
                name="flight.slo_breach", tracer=tracer,
                context=SpanContext(trace_id=trace_id, span_id=_rand_hex(8),
                                    sampled=True),
                start_ns=int(rec.t0_wall * 1e9),
            )
            span.attributes.update({
                "service.name": tracer.cfg.service_name,
                "llm_d.request_id": rec.request_id,
                "llm_d.model": rec.model,
                "llm_d.e2e_ms": round((rec.e2e_s or 0.0) * 1e3, 3),
                "llm_d.slo_ms": self.slo_ms,
                "llm_d.finish_reason": rec.finish_reason or "",
            })
            for ev in rec.events[:64]:
                span.events.append({
                    "name": ev["event"],
                    "time_ns": int((rec.t0_wall + ev["t_ms"] / 1e3) * 1e9),
                    "attributes": {k: v for k, v in ev.items()
                                   if k not in ("event", "t_ms")},
                })
            span.end()
        except Exception:
            pass  # tail capture must never take down the serving path


# --------------------------------------------------------------------------
# Shared /debug handler bodies: both servers (engine + router) expose the
# same query contract; tools/dump_flight.py renders either's output.
# --------------------------------------------------------------------------


def debug_list_response(flight: FlightRecorder, query) -> tuple:
    """``GET /debug/requests`` body: (http_status, payload). Query params:
    ``status``, ``model``, ``min_latency_ms``, ``trace``, ``limit``."""
    try:
        min_ms = (float(query["min_latency_ms"])
                  if "min_latency_ms" in query else None)
        limit = int(query.get("limit", "100"))
    except (TypeError, ValueError):
        return 400, {"error": "min_latency_ms/limit must be numeric"}
    return 200, {
        "requests": flight.snapshot(
            status=query.get("status") or None,
            model=query.get("model") or None,
            min_latency_ms=min_ms,
            trace_id=query.get("trace") or None,
            limit=limit),
        "system": flight.system_events(),
    }


def debug_detail_response(flight: FlightRecorder, request_id: str) -> tuple:
    """``GET /debug/requests/<id>`` body: (http_status, payload). The detail
    view embeds the phase-attribution ledger so "where did the time go" is
    answerable from the same fetch as "what happened", and the decision
    ledger so "why did we route here, and was it right" comes with it."""
    rec = flight.get(request_id)
    if rec is None:
        return 404, {"error": f"unknown request id {request_id!r}"}
    try:
        from llmd_tpu.obs.attribution import build_ledger

        rec["phase_ledger"] = build_ledger(rec)
    except Exception:
        pass
    try:
        from llmd_tpu.obs.decisions import build_decision

        decision = build_decision(rec)
        if decision is not None:
            rec["decision"] = decision
    except Exception:
        pass
    return 200, rec
