"""Observability plane: tracing + dashboards (SURVEY §5, reference
docs/operations/observability/)."""

from llmd_tpu.obs.tracing import (
    Span,
    TracingConfig,
    Tracer,
    extract_traceparent,
    format_traceparent,
)

__all__ = ["Span", "Tracer", "TracingConfig", "extract_traceparent",
           "format_traceparent"]
