"""Observability plane: metrics registry + tracing + dashboards (SURVEY §5,
reference docs/operations/observability/).

Metrics registry API (``llmd_tpu.obs.metrics``)
-----------------------------------------------

A dependency-free Prometheus-style registry shared by every layer::

    from llmd_tpu.obs import Registry

    reg = Registry()
    reqs = reg.counter("llm_d_epp_requests_total", "Requests received")
    depth = reg.gauge("llm_d_epp_flow_queue_depth", "Queued requests")
    lat = reg.histogram("llmd_tpu:engine_step_duration_seconds",
                        "Step wall time", labelnames=("phase",),
                        buckets=(0.001, 0.01, 0.1, 1.0))

    reqs.inc()
    depth.set(3)
    lat.labels(phase="unified").observe(0.012)
    text = reg.expose()          # Prometheus text format, fully escaped

Semantics:

* ``counter`` / ``gauge`` / ``histogram`` / ``summary`` register a family;
  re-registering the same name returns the existing family (type-checked),
  so components can share one registry without coordination.
* ``labels(**kv)`` returns the child for one label-value set; label values
  are escaped at exposition time (``escape_label_value``) — quotes,
  backslashes, and newlines in values can never corrupt the output.
* Histograms emit cumulative ``_bucket{le=...}`` series closed by
  ``+Inf``, plus ``_sum`` and ``_count``; summaries emit ``_sum``/``_count``.
* ``set_function(fn)`` attaches a scrape-time callback to an unlabeled
  counter/gauge — how legacy counter dicts surface without dual bookkeeping.
* Everything is thread-safe: the engine step-loop thread increments while
  aiohttp handlers expose.

``register_engine_metrics`` / ``register_engine_server_metrics`` /
``register_router_metrics`` declare the full family set each layer emits
(``llmd_tpu:*``, ``vllm:*``-compat, ``llm_d_epp_*``, ``igw_*``);
``tools/lint_metrics.py`` cross-checks the Grafana dashboards, alert rules,
and PromQL cookbook against these declarations in CI.

Flight recorder (``llmd_tpu.obs.events``)
-----------------------------------------

``FlightRecorder`` keeps an always-on, bounded ring of per-request event
timelines (arrival → routing → flow control → admission → prefill/decode →
retire) queryable via ``/debug/requests`` on both servers, with SLO tail
capture force-retaining (and force-tracing) slow requests. Histograms accept
``observe(v, exemplar={"trace_id": ...})`` and render OpenMetrics exemplar
annotations so dashboards can jump from a latency bucket to the trace.
See observability/flight-recorder.md.
"""

from llmd_tpu.obs.device import DeviceMonitor, fabric_alive_subprocess
from llmd_tpu.obs.events import (
    EVENT_CATALOG,
    FlightRecorder,
    RequestRecord,
)
from llmd_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    escape_label_value,
    register_device_metrics,
    register_engine_metrics,
    register_engine_server_metrics,
    register_router_metrics,
)
from llmd_tpu.obs.tracing import (
    Span,
    TracingConfig,
    Tracer,
    extract_traceparent,
    format_traceparent,
)

__all__ = [
    "Counter",
    "DeviceMonitor",
    "EVENT_CATALOG",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Registry",
    "RequestRecord",
    "Span",
    "Summary",
    "Tracer",
    "TracingConfig",
    "escape_label_value",
    "extract_traceparent",
    "fabric_alive_subprocess",
    "format_traceparent",
    "register_device_metrics",
    "register_engine_metrics",
    "register_engine_server_metrics",
    "register_router_metrics",
]
