"""Distributed tracing — OTel-shaped, dependency-free.

Parity: reference `docs/operations/observability/tracing.md:14-157` — end-to-end
traces across proxy → EPP → sidecar → engine via W3C `traceparent` propagation,
`parentbased_traceidratio` sampling (prod default 0.1), OTLP export to a
collector. The reference wires `OTEL_*` env + `--otlp-traces-endpoint`; this
module implements the same surface in-process:

- `Tracer.start_span(name, parent=ctx)` → `Span` (context-manager), attributes,
  events, status; span/trace ids are W3C-format hex.
- Propagation: `extract_traceparent(headers)` / `span.traceparent()` — any hop
  that forwards the header joins the trace.
- Sampling: parent-based trace-id-ratio — a sampled parent forces sampling, a
  root samples iff `trace_id mod 2^56 < ratio * 2^56` (deterministic per trace,
  like OTel's TraceIdRatioBased).
- Export: `memory` (tests), `jsonl` (file, one OTLP-flavoured span per line),
  `otlp` (HTTP POST of OTLP/JSON to `<endpoint>/v1/traces`, fire-and-forget
  through a single background worker draining a bounded queue — a slow or
  absent collector drops spans and counts them in `spans_dropped` instead of
  spawning a thread per span), or `none`.

Env bootstrap mirrors the reference's knobs: `LLMD_OTEL_EXPORTER`,
`LLMD_OTEL_ENDPOINT`, `LLMD_OTEL_SAMPLE_RATIO`, `OTEL_SERVICE_NAME`.
"""

from __future__ import annotations

import json
import os
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

_TRACE_ID_BITS = 128
_RATIO_BITS = 56  # OTel TraceIdRatioBased compares the low 56 bits


def _rand_hex(nbytes: int) -> str:
    return random.getrandbits(nbytes * 8).to_bytes(nbytes, "big").hex()


@dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    sampled: bool


def extract_traceparent(headers: dict) -> Optional[SpanContext]:
    """Parse a W3C `traceparent: 00-<trace>-<span>-<flags>` header (case-insensitive
    lookup). Returns None for absent or malformed values."""
    raw = None
    for k, v in headers.items():
        if k.lower() == "traceparent":
            raw = v
            break
    if not raw:
        return None
    parts = raw.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
        flags = int(parts[3], 16)
    except ValueError:
        return None
    if int(parts[1], 16) == 0 or int(parts[2], 16) == 0:
        return None
    return SpanContext(trace_id=parts[1], span_id=parts[2], sampled=bool(flags & 1))


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


@dataclass
class Span:
    name: str
    tracer: "Tracer"
    context: SpanContext
    parent_span_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: dict[str, Any] = field(default_factory=dict)
    events: list[dict] = field(default_factory=list)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append({"name": name, "time_ns": time.time_ns(),
                            "attributes": attrs})

    def set_error(self, message: str) -> None:
        self.status = "ERROR"
        self.attributes["error.message"] = message

    def traceparent(self) -> str:
        return format_traceparent(self.context)

    def end(self) -> None:
        if self.end_ns:
            return
        self.end_ns = time.time_ns()
        if self.context.sampled:
            self.tracer._export(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.set_error(f"{type(exc).__name__}: {exc}")
        self.end()

    def to_otlp(self) -> dict:
        """One span in OTLP/JSON field naming."""
        return {
            "traceId": self.context.trace_id,
            "spanId": self.context.span_id,
            "parentSpanId": self.parent_span_id or "",
            "name": self.name,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in self.attributes.items()
            ],
            "events": [
                {"name": e["name"], "timeUnixNano": str(e["time_ns"]),
                 "attributes": [{"key": k, "value": {"stringValue": str(v)}}
                                for k, v in e["attributes"].items()]}
                for e in self.events
            ],
            "status": {"code": 2 if self.status == "ERROR" else 1},
        }


@dataclass
class TracingConfig:
    enabled: bool = False
    service_name: str = "llmd-tpu"
    sample_ratio: float = 0.1       # reference prod default (tracing.md)
    exporter: str = "memory"        # none | memory | jsonl | otlp
    jsonl_path: Optional[str] = None
    otlp_endpoint: Optional[str] = None  # e.g. http://collector:4318

    @classmethod
    def from_env(cls) -> "TracingConfig":
        exporter = os.environ.get("LLMD_OTEL_EXPORTER", "")
        return cls(
            enabled=bool(exporter),
            service_name=os.environ.get("OTEL_SERVICE_NAME", "llmd-tpu"),
            sample_ratio=float(os.environ.get("LLMD_OTEL_SAMPLE_RATIO", "0.1")),
            exporter=exporter or "none",
            jsonl_path=os.environ.get("LLMD_OTEL_JSONL_PATH"),
            otlp_endpoint=os.environ.get("LLMD_OTEL_ENDPOINT"),
        )


class Tracer:
    # bound on spans waiting for the OTLP worker; past it spans are dropped
    # (and counted) rather than buffered without limit
    OTLP_QUEUE_MAX = 1024

    def __init__(self, cfg: Optional[TracingConfig] = None) -> None:
        self.cfg = cfg or TracingConfig()
        self.spans: list[Span] = []  # memory exporter sink
        self._lock = threading.Lock()
        self._jsonl_file = None
        self.export_errors = 0
        self.spans_dropped = 0  # otlp queue overflow (guarded by _lock)
        self._otlp_queue: "queue.Queue[Optional[Span]]" = queue.Queue(
            maxsize=self.OTLP_QUEUE_MAX)
        self._otlp_worker: Optional[threading.Thread] = None

    # ------------------------------------------------------------- sampling
    def _sample_root(self, trace_id: str) -> bool:
        """TraceIdRatioBased: deterministic on the low 56 bits of the trace id."""
        if self.cfg.sample_ratio >= 1.0:
            return True
        if self.cfg.sample_ratio <= 0.0:
            return False
        low = int(trace_id, 16) & ((1 << _RATIO_BITS) - 1)
        return low < int(self.cfg.sample_ratio * (1 << _RATIO_BITS))

    # ---------------------------------------------------------------- spans
    def start_span(self, name: str, parent: Optional[SpanContext] = None,
                   **attributes: Any) -> Span:
        if parent is not None:
            # parentbased: inherit the parent's decision (tracing.md sampler)
            trace_id, sampled = parent.trace_id, parent.sampled
            parent_span_id = parent.span_id
        else:
            trace_id = _rand_hex(16)
            sampled = self.cfg.enabled and self._sample_root(trace_id)
            parent_span_id = None
        span = Span(
            name=name, tracer=self,
            context=SpanContext(trace_id=trace_id, span_id=_rand_hex(8),
                                sampled=sampled and self.cfg.enabled),
            parent_span_id=parent_span_id,
            start_ns=time.time_ns(),
        )
        span.attributes.update(attributes)
        span.attributes.setdefault("service.name", self.cfg.service_name)
        return span

    # --------------------------------------------------------------- export
    def _export(self, span: Span) -> None:
        mode = self.cfg.exporter
        if mode == "none" or not self.cfg.enabled:
            return
        if mode == "memory":
            with self._lock:
                self.spans.append(span)
                if len(self.spans) > 10_000:
                    del self.spans[:5_000]
            return
        if mode == "jsonl":
            try:
                with self._lock:
                    if self._jsonl_file is None:
                        self._jsonl_file = open(
                            self.cfg.jsonl_path or "/tmp/llmd-traces.jsonl", "a")
                    self._jsonl_file.write(json.dumps(span.to_otlp()) + "\n")
                    self._jsonl_file.flush()
            except OSError:
                self.export_errors += 1
            return
        if mode == "otlp":
            self._enqueue_otlp(span)

    def _enqueue_otlp(self, span: Optional[Span]) -> None:
        """Hand a span to the single OTLP worker (started lazily on first
        export). One daemon thread per *tracer*, not per span: under load the
        old per-span threads piled up behind a slow collector without bound.
        A full queue drops the span and counts it — export is best-effort,
        the serving path never blocks on the collector."""
        with self._lock:
            if self._otlp_worker is None:
                self._otlp_worker = threading.Thread(
                    target=self._otlp_drain, name="llmd-otlp-export",
                    daemon=True)
                self._otlp_worker.start()
        try:
            self._otlp_queue.put_nowait(span)
        except queue.Full:
            with self._lock:
                self.spans_dropped += 1

    def _otlp_drain(self) -> None:
        while True:
            span = self._otlp_queue.get()
            if span is None:  # close() sentinel
                return
            self._post_otlp(span)

    def _post_otlp(self, span: Span) -> None:
        """Fire-and-forget OTLP/JSON POST (collector absent → counted, dropped)."""
        import urllib.request

        payload = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.cfg.service_name}}]},
                "scopeSpans": [{"scope": {"name": "llmd-tpu"},
                                "spans": [span.to_otlp()]}],
            }]
        }).encode()
        try:
            req = urllib.request.Request(
                f"{self.cfg.otlp_endpoint}/v1/traces", data=payload,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2).close()
        except Exception:
            self.export_errors += 1

    def close(self) -> None:
        with self._lock:
            if self._jsonl_file is not None:
                self._jsonl_file.close()
                self._jsonl_file = None
            worker = self._otlp_worker
            self._otlp_worker = None
        if worker is not None:
            try:
                self._otlp_queue.put_nowait(None)  # wake + stop the drain
            except queue.Full:
                pass  # worker is far behind; daemon thread dies with us
            worker.join(timeout=2.0)


_GLOBAL: Optional[Tracer] = None


def global_tracer() -> Tracer:
    """Process-wide tracer bootstrapped from env (reference OTEL_* knobs)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Tracer(TracingConfig.from_env())
    return _GLOBAL
