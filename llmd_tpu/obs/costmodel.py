"""Utilization attribution plane: analytic roofline cost model + ledgers.

The live analog of PERF.md's paper math. Three pieces:

1. **Analytic cost model** — per-dispatch FLOPs and HBM bytes derived from
   the model config and the dispatch's PACKED shape (the work the compiled
   program executes, padding included: NT positions for the mixed-batch and
   verify programs, B x k slot-steps for fused decode). The matmul term is
   ``2 * active_params`` per slot position — identical to bench.py's offline
   ``flops_per_tok`` so the live MFU and the bench headline can never drift.
   The byte term is weight passes + KV page traffic (read + write) from the
   pool's per-token width. Attention score/value FLOPs are O(len * Dh) per
   token against the O(params) matmul term and are deliberately excluded,
   matching the offline formula (documented in observability/utilization.md).

2. **UtilLedger** — joins each dispatch's analytic cost with the measured
   step wall at completion into per-program achieved FLOP/s and bytes/s over
   a rolling ``LLMD_UTIL_WINDOW_S`` window, exported as
   ``llmd_tpu:program_mfu`` / ``program_mbu`` against the device-generation
   peak table (CPU -> null peaks: families stay declared, gauges export no
   samples). Also the token-goodput accounting: every slot-token of every
   dispatch lands in exactly one of ``GOODPUT_KINDS`` (committed,
   spec_rejected, padding, preempted_recompute) plus the virtual
   prefix_saved class; per program the five partition (capacity + saved),
   so fractions sum to 1 by construction — PR 13's sum-to-wall discipline
   applied to tokens. And recompile observability: ``compile_counts()``
   deltas polled at completion feed ``llmd_tpu:program_compiles_total`` and
   a compile-time histogram.

3. **Peak table** — the single source of truth for device-generation peaks
   (bf16 TFLOP/s, HBM GB/s), previously a private dict in bench.py;
   ``LLMD_UTIL_PEAKS_FILE`` overlays a JSON map for new generations without
   a code change. bench.py, tools/profile_decode.py and tools/membw.py all
   consume :func:`chip_peaks`.

Off-switch contract (mirrors obs/decisions.py): ``LLMD_UTIL_LEDGER=0``
(or ``off``/``false``/empty) is read ONCE at engine construction; the off
path constructs no ledger, stamps nothing per dispatch, and attaches no
exporter — zero overhead, test-asserted in tests/test_costmodel.py.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

# ---------------------------------------------------------------------------
# Knobs (deploy/ENV_VARS.md)
# ---------------------------------------------------------------------------


def util_ledger_enabled() -> bool:
    """Master switch, read once at engine construction (default on)."""
    return os.environ.get("LLMD_UTIL_LEDGER", "1") not in (
        "0", "false", "off", "")


def util_window_s() -> float:
    """Rolling window for the achieved-rate gauges (seconds)."""
    try:
        return max(1.0, float(os.environ.get("LLMD_UTIL_WINDOW_S", "60")))
    except ValueError:
        return 60.0


# ---------------------------------------------------------------------------
# Device-generation peak table
# ---------------------------------------------------------------------------

# (bf16 TFLOP/s, HBM GB/s) per device generation — matched by substring
# against jax's device_kind. Sources: public TPU spec sheets; v5e figures
# match the numbers PERF.md's roofline sections argue from.
CHIP_PEAKS: Dict[str, Tuple[float, float]] = {
    "TPU v5 lite": (197.0, 819.0),
    "TPU v5e": (197.0, 819.0),
    "TPU v5p": (459.0, 2765.0),
    "TPU v4": (275.0, 1228.0),
    "TPU v6e": (918.0, 1640.0),
}


def _peaks_overlay() -> Dict[str, Tuple[float, float]]:
    """CHIP_PEAKS overlaid with LLMD_UTIL_PEAKS_FILE (malformed file or rows
    degrade to the builtin table with a stderr note, never a crash)."""
    table = dict(CHIP_PEAKS)
    path = os.environ.get("LLMD_UTIL_PEAKS_FILE")
    if not path:
        return table
    try:
        with open(path) as f:
            raw = json.load(f)
        for kind, peaks in raw.items():
            tf, gb = float(peaks[0]), float(peaks[1])
            table[str(kind)] = (tf, gb)
    except (OSError, ValueError, TypeError, IndexError, KeyError) as e:
        import sys
        print(f"# costmodel: ignoring LLMD_UTIL_PEAKS_FILE {path!r}: {e}",
              file=sys.stderr)
    return table


def chip_peaks(
    device_kind: str,
    default: Optional[Tuple[float, float]] = None,
) -> Tuple[Optional[float], Optional[float]]:
    """(bf16 TFLOP/s, HBM GB/s) for a device kind, or ``default`` (None,
    None) when the generation is unknown — CPU and new chips export null
    peaks so MFU/MBU gauges go absent rather than lie. bench.py passes the
    v5e-class default to keep its historical off-table behavior."""
    table = _peaks_overlay()
    # longest-match first so "TPU v5 lite" wins over a hypothetical "TPU v5"
    for k in sorted(table, key=len, reverse=True):
        if k.lower() in (device_kind or "").lower():
            return table[k]
    return default if default is not None else (None, None)


# ---------------------------------------------------------------------------
# Analytic model: params, FLOPs, bytes
# ---------------------------------------------------------------------------


def param_count(cfg) -> int:
    """Total weight parameters (bench.py's formula, extended for MoE).

    Dense: qkvo + swiglu per layer, plus (un)tied embeddings — byte-for-byte
    the historical bench._param_count. MoE adds the expert banks (+ shared
    experts) in place of the dense FFN, plus the router.
    """
    D, L = cfg.hidden_size, cfg.num_layers
    H, Hk, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = D * (H + 2 * Hk) * Dh + H * Dh * D
    if getattr(cfg, "is_moe", False):
        Fm = cfg.moe_intermediate_size
        banks = (cfg.moe_num_experts + cfg.moe_num_shared_experts)
        ffn = 3 * D * Fm * banks + D * cfg.moe_num_experts  # experts + router
    else:
        ffn = 3 * D * cfg.intermediate_size
    emb = cfg.vocab_size * D * (1 if cfg.tie_embeddings else 2)
    return (attn + ffn) * L + emb


def active_param_count(cfg) -> int:
    """Parameters touched per token (the MFU numerator's 2N): dense = all;
    MoE = attention + top_k + shared experts + router + embeddings."""
    if not getattr(cfg, "is_moe", False):
        return param_count(cfg)
    D, L = cfg.hidden_size, cfg.num_layers
    H, Hk, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = D * (H + 2 * Hk) * Dh + H * Dh * D
    Fm = cfg.moe_intermediate_size
    active = cfg.moe_top_k + cfg.moe_num_shared_experts
    ffn = 3 * D * Fm * active + D * cfg.moe_num_experts
    emb = cfg.vocab_size * D * (1 if cfg.tie_embeddings else 2)
    return (attn + ffn) * L + emb


def bytes_per_param(cfg, quantize_weights: Optional[str]) -> int:
    """Weight-stream bytes per parameter: int8 weight-only serves ~1 (per-
    channel scales are negligible), else checkpoint dtype width."""
    if quantize_weights == "int8":
        return 1
    return 2 if cfg.dtype == "bfloat16" else 4


def weight_bytes(cfg, quantize_weights: Optional[str] = None) -> int:
    """Bytes one full weight pass streams from HBM."""
    return param_count(cfg) * bytes_per_param(cfg, quantize_weights)


def flops_per_token(cfg) -> float:
    """Matmul FLOPs per slot position: 2 * active params (the shared
    numerator of bench's decode_mfu and the live program_mfu)."""
    return 2.0 * active_param_count(cfg)


def kv_bytes_per_token(cfg, kv_cache_dtype: Optional[str] = None) -> int:
    """Pool bytes per cached token: planes x heads x head-width x dtype.
    GQA stores k+v planes; MLA stores one latent(+rope) plane. fp8 KV
    halves the width."""
    dtype_bytes = 1 if kv_cache_dtype == "fp8" else (
        2 if cfg.dtype == "bfloat16" else 4)
    planes = 1 if getattr(cfg, "is_mla", False) else 2
    return planes * cfg.kv_cache_heads * cfg.kv_cache_head_dim * dtype_bytes


def decode_hbm_gb_per_token(cfg, quantize_weights: Optional[str],
                            max_batch_size: int) -> float:
    """bench.py's offline per-token weights traffic: one full weight pass
    amortized over the decode batch (GB/token)."""
    return (weight_bytes(cfg, quantize_weights) / 1e9
            / max(1, max_batch_size))


def moe_comm_bytes_per_token(cfg) -> int:
    """MoE dispatch/combine traffic per slot token: every layer ships each
    of the top-k routed copies of the D-wide activation to its expert and
    back (2 hops — DeepEP's dispatch + combine, lax.all_to_all here). Dense
    models route nothing. Counted in ``dispatch_cost`` so ``program_mbu``
    sees the all-to-all bytes the roofline previously ignored."""
    if not getattr(cfg, "is_moe", False):
        return 0
    act_bytes = 2 if cfg.dtype == "bfloat16" else 4
    return (cfg.num_layers * cfg.moe_top_k * cfg.hidden_size
            * act_bytes * 2)


@dataclass(frozen=True)
class DispatchCost:
    """Analytic cost of ONE compiled-program dispatch, from its packed shape.

    ``slot_tokens`` is the padded capacity the program actually computes
    (NT, or B x k for fused decode) — the goodput denominator and the FLOPs
    multiplier: padding burns real FLOPs, which is exactly what MFU should
    see and goodput should indict.
    """

    flops: float
    hbm_bytes: float
    slot_tokens: int
    # MoE all-to-all dispatch+combine traffic (slot_tokens x k x D x bytes x
    # 2 hops x layers); already folded into hbm_bytes, kept separate so the
    # bench JSON / ledger can report the comm share on its own.
    moe_comm_bytes: float = 0.0


def dispatch_cost(cfg, *, slot_tokens: int, weight_passes: int = 1,
                  kv_read_tokens: int = 0, kv_write_tokens: int = 0,
                  quantize_weights: Optional[str] = None,
                  kv_cache_dtype: Optional[str] = None) -> DispatchCost:
    """Cost of one dispatch: ``2 * active_params`` FLOPs per slot token;
    bytes = weight passes + KV page reads/writes + MoE dispatch/combine
    comm. Monotone in every token argument (test-asserted)."""
    kvb = kv_bytes_per_token(cfg, kv_cache_dtype)
    moe_comm = float(moe_comm_bytes_per_token(cfg)) * max(0, slot_tokens)
    return DispatchCost(
        flops=flops_per_token(cfg) * max(0, slot_tokens),
        hbm_bytes=(float(weight_bytes(cfg, quantize_weights)) * weight_passes
                   + float(kvb) * (max(0, kv_read_tokens)
                                   + max(0, kv_write_tokens))
                   + moe_comm),
        slot_tokens=max(0, slot_tokens),
        moe_comm_bytes=moe_comm,
    )


# ---------------------------------------------------------------------------
# Goodput taxonomy
# ---------------------------------------------------------------------------

GOODPUT_KINDS = ("committed", "spec_rejected", "padding",
                 "preempted_recompute", "prefix_saved")


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class UtilLedger:
    """Per-program utilization + goodput + recompile accounting.

    The engine calls :meth:`record` once per completed dispatch from the
    step loop (single-threaded); gauges read through scrape-time callbacks
    from the metrics thread, so mutation happens under a lock. All inputs
    are host integers the dispatch sites already compute — no device reads.
    """

    def __init__(self, model_cfg, *, device_kind: str = "",
                 quantize_weights: Optional[str] = None,
                 kv_cache_dtype: Optional[str] = None,
                 window_s: Optional[float] = None,
                 peaks: Optional[Tuple[Optional[float],
                                       Optional[float]]] = None,
                 now=time.monotonic):
        self.cfg = model_cfg
        self.quantize_weights = quantize_weights
        self.kv_cache_dtype = kv_cache_dtype
        self.window_s = util_window_s() if window_s is None else window_s
        tf, gb = chip_peaks(device_kind) if peaks is None else peaks
        self.peak_flops = tf * 1e12 if tf else None
        self.peak_bytes = gb * 1e9 if gb else None
        self._now = now
        self._lock = threading.RLock()
        # program -> kind -> tokens
        self._tokens: Dict[str, Dict[str, int]] = {}
        # program -> [flops, bytes, busy_s, dispatches] cumulative
        self._cost: Dict[str, list] = {}
        # program -> deque[(t, flops, bytes)] for the rolling-rate gauges
        self._events: Dict[str, collections.deque] = {}
        # recompile watch: last compile_counts() snapshot + per-program total
        self._compiles_seen: Dict[str, int] = {}
        self._compiles: Dict[str, int] = collections.defaultdict(int)
        self._metrics = None  # bound by attach_util_exporter

    # -- recording ---------------------------------------------------------

    def cost(self, program: str, *, slot_tokens: int, weight_passes: int = 1,
             kv_read_tokens: int = 0, kv_write_tokens: int = 0) -> DispatchCost:
        """Dispatch-site helper: analytic cost with this engine's weight/KV
        byte widths baked in."""
        del program  # cost is shape-only; kept for call-site readability
        return dispatch_cost(
            self.cfg, slot_tokens=slot_tokens, weight_passes=weight_passes,
            kv_read_tokens=kv_read_tokens, kv_write_tokens=kv_write_tokens,
            quantize_weights=self.quantize_weights,
            kv_cache_dtype=self.kv_cache_dtype)

    def record(self, program: str, cost: DispatchCost, duration_s: float, *,
               committed: int = 0, spec_rejected: int = 0,
               preempted_recompute: int = 0, prefix_saved: int = 0,
               compile_counts: Optional[Dict[str, int]] = None) -> None:
        """Join one completed dispatch's analytic cost with its measured
        step wall and classify its slot-tokens. ``padding`` is the residual
        ``slot_tokens - (committed + spec_rejected + preempted_recompute)``,
        clamped at 0, so per-program fractions sum to 1 by construction."""
        real = committed + spec_rejected + preempted_recompute
        padding = max(0, cost.slot_tokens - real)
        t = self._now()
        with self._lock:
            tk = self._tokens.setdefault(
                program, {k: 0 for k in GOODPUT_KINDS})
            tk["committed"] += committed
            tk["spec_rejected"] += spec_rejected
            tk["padding"] += padding
            tk["preempted_recompute"] += preempted_recompute
            tk["prefix_saved"] += prefix_saved
            c = self._cost.setdefault(program, [0.0, 0.0, 0.0, 0, 0.0])
            c[0] += cost.flops
            c[1] += cost.hbm_bytes
            c[2] += max(0.0, duration_s)
            c[3] += 1
            c[4] += cost.moe_comm_bytes
            ev = self._events.setdefault(
                program, collections.deque())
            ev.append((t, cost.flops, cost.hbm_bytes))
            self._trim(ev, t)
        m = self._metrics
        if m is not None:
            gp = m.goodput_tokens
            for kind, n in (("committed", committed),
                            ("spec_rejected", spec_rejected),
                            ("padding", padding),
                            ("preempted_recompute", preempted_recompute),
                            ("prefix_saved", prefix_saved)):
                if n:
                    gp.labels(program=program, kind=kind).inc(n)
        if compile_counts is not None:
            self._note_compiles(program, compile_counts, duration_s)

    def _trim(self, ev: collections.deque, t: float) -> None:
        horizon = t - self.window_s
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def _note_compiles(self, program: str, counts: Dict[str, int],
                       duration_s: float) -> None:
        """Fold a compile_counts() snapshot: any program whose cache grew
        since the last snapshot gets the delta counted; the program whose
        dispatch just completed additionally observes its step wall into the
        compile-time histogram (the compile dominated that step)."""
        m = self._metrics
        with self._lock:
            for prog, n in counts.items():
                prev = self._compiles_seen.get(prog, 0)
                if n > prev:
                    delta = n - prev
                    self._compiles[prog] += delta
                    if m is not None:
                        m.program_compiles.labels(program=prog).inc(delta)
                        if prog == program:
                            m.program_compile_seconds.labels(
                                program=prog).observe(max(0.0, duration_s))
                self._compiles_seen[prog] = max(prev, n)

    # -- reading -----------------------------------------------------------

    def programs(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._tokens))

    def totals(self) -> Dict[str, Dict[str, int]]:
        """program -> kind -> cumulative tokens (deep copy)."""
        with self._lock:
            return {p: dict(t) for p, t in self._tokens.items()}

    def fractions(self, program: str) -> Dict[str, float]:
        """Goodput fractions for one program; values sum to 1 (empty dict
        before the first dispatch)."""
        with self._lock:
            tk = self._tokens.get(program)
            if not tk:
                return {}
            total = sum(tk.values())
            if total <= 0:
                return {}
            return {k: v / total for k, v in tk.items()}

    def padding_efficiency(self, program: str) -> Optional[float]:
        """Real packed positions / slot capacity, cumulative. In (0,1] once
        the program has carried any real token; None before any dispatch."""
        with self._lock:
            tk = self._tokens.get(program)
            if not tk:
                return None
            real = (tk["committed"] + tk["spec_rejected"]
                    + tk["preempted_recompute"])
            cap = real + tk["padding"]
            if cap <= 0:
                return None
            return real / cap

    def achieved(self, program: str) -> Tuple[Optional[float],
                                              Optional[float]]:
        """(FLOP/s, bytes/s) over the rolling window; None before data."""
        t = self._now()
        with self._lock:
            ev = self._events.get(program)
            if not ev:
                return (None, None)
            self._trim(ev, t)
            if not ev:
                return (None, None)
            flops = sum(e[1] for e in ev)
            byts = sum(e[2] for e in ev)
            span = max(t - ev[0][0], 1e-3)
        return (flops / span, byts / span)

    def mfu(self, program: str) -> Optional[float]:
        if self.peak_flops is None:
            return None
        f, _ = self.achieved(program)
        return None if f is None else f / self.peak_flops

    def mbu(self, program: str) -> Optional[float]:
        if self.peak_bytes is None:
            return None
        _, b = self.achieved(program)
        return None if b is None else b / self.peak_bytes

    def moe_comm_total(self) -> float:
        """Cumulative MoE all-to-all bytes across all programs — the bench
        JSON ``moe_comm_bytes`` key reads this, so the offline number and
        the hbm_bytes fold that feeds program_mbu share one accumulator."""
        with self._lock:
            return sum(c[4] for c in self._cost.values())

    def compiles(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._compiles)

    def recompiles(self) -> int:
        """Compiles beyond the first per program — 0 in healthy steady
        state (the bench provenance key and the RecompileStorm numerator)."""
        with self._lock:
            return sum(max(0, n - 1) for n in self._compiles.values())

    # -- scrape-time callbacks --------------------------------------------

    def _gauge_samples(self, fn) -> Iterable[Tuple[Dict[str, str], float]]:
        for p in self.programs():
            v = fn(p)
            if v is not None:
                yield ({"program": p}, v)

    def mfu_samples(self):
        return self._gauge_samples(self.mfu)

    def mbu_samples(self):
        return self._gauge_samples(self.mbu)

    def flops_samples(self):
        return self._gauge_samples(lambda p: self.achieved(p)[0])

    def bytes_samples(self):
        return self._gauge_samples(lambda p: self.achieved(p)[1])

    def padding_samples(self):
        return self._gauge_samples(self.padding_efficiency)


def attach_util_exporter(ledger: UtilLedger, metrics) -> None:
    """Bind the ledger to an EngineMetrics: counters increment inline at
    record() time; the rate/ratio gauges attach scrape-time callbacks (the
    device-HBM-gauge pattern, so label sets track programs as they run)."""
    ledger._metrics = metrics
    metrics.program_mfu.set_labels_function(ledger.mfu_samples)
    metrics.program_mbu.set_labels_function(ledger.mbu_samples)
    metrics.program_flops.set_labels_function(ledger.flops_samples)
    metrics.program_bytes.set_labels_function(ledger.bytes_samples)
    metrics.padding_efficiency.set_labels_function(ledger.padding_samples)
