"""Device-plane observability: HBM telemetry, fabric liveness, step watchdog,
and on-demand profiler capture.

The host plane (metrics registry, flight recorder, tracing) sees everything
*around* the accelerator but nothing *inside* it: the int8-b128 fabric death
(PERF.md Round 6) was diagnosed only after a 1500s bench timeout via an
out-of-band bash poller, and the pool controller's ``/health`` sweep retires
killed replicas but cannot see an engine whose asyncio loop is alive while
its TPU is hung mid-step. ``DeviceMonitor`` closes that gap with four
coordinated parts, all surfaced through the same metrics/events/health
contracts the rest of the stack already uses:

* **HBM telemetry** — ``device.memory_stats()`` sampled on a poll thread and
  exported as per-device gauges via scrape-time callbacks
  (``llmd_tpu:device_hbm_bytes_in_use|peak_bytes|limit_bytes{device=...}``).
  Backends without memory stats (CPU) simply export no series — never crash.
* **Fabric liveness** — a tiny device op executed on a dedicated worker
  thread under ``LLMD_FABRIC_PROBE_TIMEOUT_S``. A wedged fabric parks the
  worker, not the caller: the scheduler times out, flips
  ``llmd_tpu:device_fabric_alive`` to 0, increments the failure counter, and
  emits a ``fabric_dead`` flight event. The worker finishing later flips it
  back (``fabric_recovered``).
* **Step watchdog** — the engine dispatch loop stamps ``heartbeat()`` once
  per iteration (a bare monotonic attribute write, no lock). A watchdog
  thread seeing pending work with no heartbeat for ``LLMD_WATCHDOG_STALL_S``
  emits ``engine_stalled``, sets the stall gauge, and makes
  ``unhealthy_reason()`` non-None — the engine server turns that into a 503
  ``/health`` with a structured reason, which the PoolController health sweep
  and router circuit breakers already route around. Device fault → automatic
  replica retirement, no new control-plane machinery.
* **Profiler capture** — ``capture_profile(seconds)`` wraps
  ``jax.profiler.start_trace``/``stop_trace`` into ``LLMD_PROFILE_DIR`` (one
  capture at a time; the server returns 409 while busy). The engine step loop
  is annotated per phase (``llmd.unified`` / ``llmd.decode_dispatch`` /
  ``llmd.decode_process`` / ``llmd.spec_verify`` / ``llmd.mask_build``) so a
  capture attributes device time to the same phase names the step-duration
  histogram exports.

Threading: the watchdog and telemetry threads never touch the engine lock (a
hung ``step()`` holds it — that's the failure being detected). Pending work
is read via an injected ``pending_fn`` whose default is a GIL-atomic dict
truthiness check, and the heartbeat is a bare attribute. Metric mutations and
flight emissions happen *outside* ``self._lock`` — the registry has its own
lock and the scrape path reads our HBM cache through it, so nesting them
would order registry-lock → monitor-lock against monitor-lock →
registry-lock.

``fabric_alive_subprocess`` is the out-of-process variant shared with
``tools/r05_campaign.py``: backend init is process-fatal when the fabric is
wedged, so post-timeout probes from a bench harness must fork.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from llmd_tpu.obs.metrics import Registry, register_device_metrics

__all__ = ["DeviceMonitor", "ProfileBusy", "fabric_alive_subprocess",
           "default_probe_op"]


class ProfileBusy(RuntimeError):
    """A profiler capture is already in progress (one window at a time)."""


def fabric_alive_subprocess(timeout_s: float = 90.0,
                            platform: str = "tpu",
                            cwd: Optional[str] = None) -> bool:
    """Probe the accelerator fabric in a throwaway subprocess.

    Backend init is process-fatal when the fabric is wedged, so a probe
    issued *after* something already timed out cannot run in-process — the
    serving/bench process would hang or die with it. Much cheaper than a
    full preflight: backend init + device count, nothing else. Shared by
    ``tools/r05_campaign.py`` (post-timeout fast-skip decision) and operator
    runbooks so bench and serving agree on what "fabric dead" means.
    """
    cmd = [sys.executable, "-c",
           f"import jax; print(len(jax.devices({platform!r})))"]
    try:
        p = subprocess.run(cmd, cwd=cwd, capture_output=True, text=True,
                           timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return False
    out = p.stdout.strip()
    return p.returncode == 0 and out.isdigit() and int(out) > 0


def default_probe_op() -> None:
    """The in-process liveness op: a tiny multiply forced to completion.

    Small enough to be free on a healthy device (microseconds), but it
    round-trips dispatch → execute → readback, which is exactly the path a
    wedged fabric hangs."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 8), dtype=jnp.float32)
    jax.block_until_ready(x * 2.0)


def _env_f(name: str, default: str) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class DeviceMonitor:
    """Per-replica device-plane monitor. Owned by the engine server that
    created the engine; wide-EP frontends sharing an engine share the
    monitor via ``engine.monitor``."""

    def __init__(self, registry: Registry,
                 flight=None,
                 devices=None,
                 probe_op: Optional[Callable[[], None]] = None,
                 pending_fn: Optional[Callable[[], bool]] = None,
                 stall_s: Optional[float] = None,
                 probe_interval_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 profile_dir: Optional[str] = None) -> None:
        self.metrics = register_device_metrics(registry)
        self.flight = flight
        self._devices = devices  # None → jax.local_devices() at start()
        self._probe_op = probe_op or default_probe_op
        self._pending_fn = pending_fn
        self.stall_s = (float(stall_s) if stall_s is not None
                        else _env_f("LLMD_WATCHDOG_STALL_S", "120"))
        self.probe_interval_s = (
            float(probe_interval_s) if probe_interval_s is not None
            else _env_f("LLMD_FABRIC_PROBE_INTERVAL_S", "30"))
        self.probe_timeout_s = (
            float(probe_timeout_s) if probe_timeout_s is not None
            else _env_f("LLMD_FABRIC_PROBE_TIMEOUT_S", "20"))
        self.poll_s = max(0.05, float(poll_s) if poll_s is not None
                          else _env_f("LLMD_DEVICE_POLL_S", "10"))
        self.profile_dir = (profile_dir
                            or os.environ.get("LLMD_PROFILE_DIR",
                                              "/tmp/llmd-profiles"))
        self._lock = threading.Lock()
        # heartbeat: bare monotonic stamp, written lock-free by the dispatch
        # loop (heartbeat()) and read lock-free by the watchdog — a hung
        # step() holds the engine lock, so nothing here may wait on one.
        self._beat = time.monotonic()
        self._stalled = False            # guarded by _lock
        self._stall_age_s = 0.0          # guarded by _lock
        self._fabric_alive = True        # guarded by _lock
        self._hbm: Dict[str, Tuple[float, float, float]] = {}  # guarded by _lock
        self._profiling = False          # guarded by _lock
        self._probe_busy = False   # worker-owned bool; scheduler reads it
        self._probe_result: Tuple[bool, float] = (True, 0.0)
        self._probe_req = threading.Event()
        self._probe_done = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        if self._devices is None:
            try:
                import jax
                self._devices = list(jax.local_devices())
            except Exception:
                self._devices = []
        self.metrics.fabric_alive.set(1)
        self.metrics.engine_stalled.set(0)
        self.metrics.heartbeat_age.set_function(
            lambda: max(0.0, time.monotonic() - self._beat))
        self.metrics.hbm_bytes_in_use.set_labels_function(
            lambda: self._hbm_field(0))
        self.metrics.hbm_peak_bytes.set_labels_function(
            lambda: self._hbm_field(1))
        self.metrics.hbm_limit_bytes.set_labels_function(
            lambda: self._hbm_field(2))
        if self.stall_s > 0:
            t = threading.Thread(target=self._watchdog_loop,
                                 name="llmd-watchdog", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._telemetry_loop,
                             name="llmd-device-telemetry", daemon=True)
        t.start()
        self._threads.append(t)
        if self.probe_interval_s > 0:
            t = threading.Thread(target=self._probe_worker,
                                 name="llmd-fabric-probe", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # the probe worker may be wedged inside the device op — that is the
        # scenario being monitored — so joins are bounded, never indefinite
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads = []

    # ---------------------------------------------------------- heartbeat
    def heartbeat(self) -> None:
        """Stamped by the engine dispatch loop once per iteration. Bare
        attribute write: must stay lock-free (see module docstring)."""
        self._beat = time.monotonic()

    def unhealthy_reason(self) -> Optional[dict]:
        """Structured health verdict for the engine server's ``/health``:
        None when fine, else a dict the PoolController sweep can log."""
        with self._lock:
            if self._stalled:
                return {"reason": "engine_stalled",
                        "heartbeat_age_s": round(self._stall_age_s, 3),
                        "stall_s": self.stall_s}
            if not self._fabric_alive:
                return {"reason": "fabric_dead",
                        "probe_timeout_s": self.probe_timeout_s}
        return None

    # ----------------------------------------------------------- watchdog
    def _watchdog_loop(self) -> None:
        tick = min(1.0, max(0.05, self.stall_s / 4.0))
        while not self._stop.wait(tick):
            age = time.monotonic() - self._beat
            try:
                pending = bool(self._pending_fn()) if self._pending_fn else False
            except Exception:
                pending = False
            stalled = pending and age >= self.stall_s
            with self._lock:
                was = self._stalled
                self._stalled = stalled
                if stalled:
                    self._stall_age_s = age
            if stalled and not was:
                self.metrics.engine_stalled.set(1)
                self.metrics.engine_stalls.inc()
                if self.flight is not None:
                    self.flight.record_system(
                        "engine_stalled",
                        heartbeat_age_s=round(age, 3), stall_s=self.stall_s)
            elif was and not stalled:
                self.metrics.engine_stalled.set(0)
                if self.flight is not None:
                    self.flight.record_system(
                        "engine_recovered", heartbeat_age_s=round(age, 3))

    # -------------------------------------------------- telemetry + probe
    def _telemetry_loop(self) -> None:
        last_probe = -float("inf")  # probe immediately on startup
        while not self._stop.is_set():
            self._poll_hbm()
            now = time.monotonic()
            if (self.probe_interval_s > 0
                    and now - last_probe >= self.probe_interval_s):
                last_probe = now
                self._run_probe_cycle()
            self._stop.wait(self.poll_s)

    def _poll_hbm(self) -> None:
        samples: Dict[str, Tuple[float, float, float]] = {}
        for d in self._devices or ():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue  # CPU / backends without stats: export nothing
            label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            samples[label] = (
                float(stats.get("bytes_in_use", 0)),
                float(stats.get("peak_bytes_in_use", 0)),
                float(stats.get("bytes_limit", 0)),
            )
        with self._lock:
            self._hbm = samples

    def _hbm_field(self, idx: int) -> List[Tuple[dict, float]]:
        """Scrape-time callback body for the per-device HBM gauges."""
        with self._lock:
            snap = dict(self._hbm)
        return [({"device": dev}, vals[idx]) for dev, vals in snap.items()]

    def _probe_worker(self) -> None:
        """Persistent worker executing the device op; a wedged fabric parks
        this thread, never the scheduler that timed out waiting on it."""
        while not self._stop.is_set():
            if not self._probe_req.wait(timeout=0.1):
                continue
            self._probe_req.clear()
            self._probe_busy = True
            t0 = time.monotonic()
            try:
                self._probe_op()
                ok = True
            except Exception:
                ok = False
            self._probe_result = (ok, time.monotonic() - t0)
            self._probe_busy = False
            self._probe_done.set()

    def _run_probe_cycle(self) -> None:
        if self._probe_busy:
            # previous probe still wedged inside the device op — don't stack
            # requests, just count the cycle as failed
            self._apply_probe(False, None)
            return
        self._probe_done.clear()
        self._probe_req.set()
        if self._probe_done.wait(timeout=self.probe_timeout_s):
            ok, dt = self._probe_result
            self._apply_probe(ok, dt)
        else:
            self._apply_probe(False, None)

    def _apply_probe(self, ok: bool, dt: Optional[float]) -> None:
        with self._lock:
            was = self._fabric_alive
            self._fabric_alive = ok
        if ok:
            self.metrics.fabric_alive.set(1)
            if dt is not None:
                self.metrics.fabric_probe_seconds.observe(dt)
            if not was and self.flight is not None:
                self.flight.record_system("fabric_recovered")
        else:
            self.metrics.fabric_alive.set(0)
            self.metrics.fabric_probe_failures.inc()
            if was and self.flight is not None:
                self.flight.record_system(
                    "fabric_dead", probe_timeout_s=self.probe_timeout_s)

    # ------------------------------------------------------------ profile
    def capture_profile(self, seconds: float) -> dict:
        """Capture one ``jax.profiler`` window into ``profile_dir``.

        Blocking (the caller runs it in an executor); one capture at a time —
        a concurrent call raises :class:`ProfileBusy` and the server maps
        that to 409. Returns ``{dir, files, bytes, seconds}`` describing the
        artifact."""
        seconds = max(0.1, min(float(seconds), 60.0))
        with self._lock:
            if self._profiling:
                raise ProfileBusy("a profiler capture is already in progress")
            self._profiling = True
        try:
            import jax
            out_dir = os.path.join(
                self.profile_dir,
                time.strftime("%Y%m%d-%H%M%S", time.gmtime()))
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            files: List[str] = []
            total = 0
            for root, _dirs, names in os.walk(out_dir):
                for name in names:
                    path = os.path.join(root, name)
                    files.append(os.path.relpath(path, out_dir))
                    total += os.path.getsize(path)
            self.metrics.profile_captures.inc()
            if self.flight is not None:
                self.flight.record_system(
                    "profile_capture", seconds=seconds, dir=out_dir,
                    files=len(files), bytes=total)
            return {"dir": out_dir, "files": sorted(files), "bytes": total,
                    "seconds": seconds}
        finally:
            with self._lock:
                self._profiling = False
