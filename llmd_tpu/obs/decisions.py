"""Decision observability: why routing chose what it chose, and was it right.

The phase ledger (obs/attribution.py) answers "where did the time GO"; this
module answers "why did we choose this endpoint, and did the decision pay
off". Three accounts are folded per request at retirement:

* **Routing decision ledger** — the scheduler's filter eliminations, the
  weighted per-scorer score breakdown for the chosen endpoint and runner-up,
  picker tie width, and retry/hedge re-schedules, emitted as a
  ``route_decision`` flight event by the router and folded here.
* **Predictor calibration** — the `predicted-latency-producer` stamps its
  TTFT/e2e estimates on the decision event; at retire they are joined against
  the observed TTFT (``response`` event) and wall clock, exporting signed
  calibration-error histograms and a rolling absolute-percentage-error gauge
  per model (``llmd_tpu:predictor_calibration_*``). `tools/predictor_accuracy.py
  --from-metrics` consumes these families from a live scrape.
* **Lever efficiency** — KV-plane pulls (blocks pulled × estimated re-prefill
  tokens saved, degraded-path fallbacks) and spec-decode economics (drafted /
  accepted / wasted verify positions, per-sequence arm/disarm flips), folded
  into ``llmd_tpu:decision_*`` families plus a per-request **regret** series:
  chosen-endpoint weighted score minus the best alternative's, bucketed by
  whether the request went on to breach its SLO.

Like the phase ledger, ``build_decision`` is a pure fold over the
``to_dict()`` record shape, so the same function serves the live exporter
(chained onto ``FlightRecorder.on_finish`` after the phase exporter), the
``/debug/requests/<id>`` detail view, and ``tools/dump_flight.py
--decisions`` against offline dumps.

Knobs (read ONCE at component construction — when the ledger is off, the
scheduler never allocates decision detail and no exporter is attached, so
the off path costs literally nothing per request):

* ``LLMD_DECISION_LEDGER``      — "1" (default) records ledgers, "0" disables
* ``LLMD_DECISION_REGRET_TOPK`` — ranked candidates kept per profile (def 3)
* ``LLMD_DECISION_CALIB_WINDOW``— rolling APE window per (objective, model)
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "decisions_enabled",
    "regret_topk",
    "calibration_window",
    "build_decision",
    "CalibrationWindows",
    "attach_decision_exporter",
]


def decisions_enabled() -> bool:
    """Master switch; components cache this at construction time."""
    return os.environ.get("LLMD_DECISION_LEDGER", "1") not in ("0", "false", "")


def regret_topk() -> int:
    try:
        return max(1, int(os.environ.get("LLMD_DECISION_REGRET_TOPK", "3")))
    except ValueError:
        return 3


def calibration_window() -> int:
    try:
        return max(8, int(os.environ.get("LLMD_DECISION_CALIB_WINDOW", "256")))
    except ValueError:
        return 256


# ---------------------------------------------------------------------------
# the fold: flight record → decision ledger


def _router_ledger(rec: dict, events: list, schedules: list) -> dict:
    final = schedules[-1]
    ledger: dict = {
        "plane": "router",
        "schedules": len(schedules),
        "reschedules": {
            "retry": sum(1 for e in events if e.get("event") == "retry"),
            "hedge": sum(1 for e in events if e.get("event") == "hedge"),
        },
        "profiles": final.get("profiles") or {},
        "slo_breached": any(e.get("event") == "slo_breach" for e in events),
    }
    if final.get("regret") is not None:
        ledger["regret"] = final["regret"]
    for k in ("resilience_dropped", "excluded", "breakers", "kv_plane", "pd"):
        if final.get(k):
            ledger[k] = final[k]

    # calibration join: the final schedule's predicted stamps vs observed.
    # TTFT only exists on streamed responses; e2e only on a clean finish
    # (a retried/errored wall clock measures the retry loop, not the model).
    calib: dict = {}
    resp = next((e for e in reversed(events)
                 if e.get("event") == "response"), None)
    obs_ttft = resp.get("ttft_ms") if resp else None
    pred_ttft = final.get("predicted_ttft_ms")
    if pred_ttft is not None and obs_ttft is not None:
        calib["ttft_predicted_ms"] = pred_ttft
        calib["ttft_observed_ms"] = obs_ttft
        calib["ttft_error_ms"] = round(float(obs_ttft) - float(pred_ttft), 3)
    pred_e2e = final.get("predicted_e2e_ms")
    wall = rec.get("latency_ms")
    if (pred_e2e is not None and wall
            and rec.get("status") == "finished"
            and not ledger["reschedules"]["retry"]):
        calib["e2e_predicted_ms"] = pred_e2e
        calib["e2e_observed_ms"] = round(float(wall), 3)
        calib["e2e_error_ms"] = round(float(wall) - float(pred_e2e), 3)
    if calib:
        ledger["calibration"] = calib

    # KV lever, router view: pulls the scheduler stamped onto the forward
    stamped = [e for e in events if e.get("event") == "kv_pull_stamped"]
    if stamped:
        ledger["kv"] = {
            "stamped": len(stamped),
            "blocks": sum(int(e.get("blocks") or 0) for e in stamped),
            "saved_tokens_est": sum(int(e.get("saved_tokens_est") or 0)
                                    for e in stamped),
        }
    return ledger


def _engine_ledger(rec: dict, events: list) -> Optional[dict]:
    retired = next((e for e in reversed(events)
                    if e.get("event") == "retired"), None)
    pulls = [e for e in events if e.get("event") == "kv_pull"]
    ledger: dict = {"plane": "engine"}
    if retired is not None:
        drafted = int(retired.get("spec_drafted") or 0)
        flips = int(retired.get("spec_flips") or 0)
        if drafted or flips:
            accepted = int(retired.get("spec_accepted") or 0)
            ledger["spec"] = {
                "drafted": drafted,
                "accepted": accepted,
                "wasted": max(0, drafted - accepted),
                "flips": flips,
            }
        if retired.get("cached_tokens"):
            ledger["cached_tokens"] = int(retired["cached_tokens"])
    if pulls:
        last = pulls[-1]
        ledger["kv"] = {
            "outcome": last.get("outcome"),
            "blocks": sum(int(e.get("blocks") or 0) for e in pulls),
            "ms": round(sum(float(e.get("ms") or 0.0) for e in pulls), 3),
        }
    return ledger if len(ledger) > 1 else None


def build_decision(rec: dict) -> Optional[dict]:
    """Fold one flight record (``to_dict()`` shape) into a decision ledger,
    or None when the record carries nothing decision-relevant (ledger off,
    engine request with no spec/KV activity, pre-decision-plane dump)."""
    events = rec.get("events") or []
    schedules = [e for e in events if e.get("event") == "route_decision"]
    if schedules:
        return _router_ledger(rec, events, schedules)
    return _engine_ledger(rec, events)


# ---------------------------------------------------------------------------
# rolling calibration windows (the APE gauge's backing store)


class CalibrationWindows:
    """Bounded per-(objective, model) windows of absolute percentage errors.

    ``samples()`` is the scrape-time callback body for the
    ``llmd_tpu:predictor_calibration_ape`` gauge's ``set_labels_function`` —
    label sets track whatever (objective, model) pairs actually retired, the
    window bounds memory per pair."""

    def __init__(self, window: Optional[int] = None) -> None:
        self.window = window or calibration_window()
        self._lock = threading.Lock()
        self._w: Dict[Tuple[str, str], deque] = {}

    def add(self, objective: str, model: str,
            observed_ms: float, error_ms: float) -> None:
        ape = abs(float(error_ms)) / max(abs(float(observed_ms)), 1e-6)
        with self._lock:
            d = self._w.get((objective, model))
            if d is None:
                d = deque(maxlen=self.window)
                self._w[(objective, model)] = d
            d.append(ape)

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [({"objective": o, "model": m},
                     round(sum(d) / len(d), 6))
                    for (o, m), d in self._w.items() if d]


# ---------------------------------------------------------------------------
# live exporter


def attach_decision_exporter(flight, metrics, plane: str = "router",
                             windows: Optional[CalibrationWindows] = None,
                             ) -> Callable[[dict], None]:
    """Chain a decision exporter onto ``flight.on_finish``.

    ``on_finish`` is a single slot and the phase exporter (attribution.py)
    claims it first, so this hook wraps and forwards to whatever was
    installed before it. Router metrics get regret / calibration / KV
    families; engine metrics get the spec-economics families. The hook must
    never take down retirement: failures are swallowed per stage."""
    prev = flight.on_finish
    if plane == "router":
        windows = windows or CalibrationWindows()
        metrics.predictor_calibration_ape.set_labels_function(windows.samples)

    def _export(rec: dict) -> None:
        if prev is not None:
            try:
                prev(rec)
            except Exception:
                pass
        try:
            ledger = build_decision(rec)
            if ledger is None:
                return
            metrics.decision_ledgers.labels(plane=ledger["plane"]).inc()
            if ledger["plane"] == "router":
                _export_router(rec, ledger)
            else:
                _export_engine(ledger)
        except Exception:
            pass

    def _export_router(rec: dict, ledger: dict) -> None:
        regret = ledger.get("regret")
        if regret is not None:
            breached = "yes" if ledger.get("slo_breached") else "no"
            metrics.decision_regret.labels(slo_breached=breached).observe(
                float(regret))
        for kind, n in (ledger.get("reschedules") or {}).items():
            if n:
                metrics.decision_reschedules.labels(kind=kind).inc(n)
        calib = ledger.get("calibration") or {}
        model = rec.get("model") or ""
        for objective in ("ttft", "e2e"):
            err = calib.get(f"{objective}_error_ms")
            if err is None:
                continue
            metrics.predictor_calibration_error.labels(
                objective=objective, model=model).observe(float(err))
            windows.add(objective, model,
                        calib.get(f"{objective}_observed_ms") or 0.0, err)
        kv = ledger.get("kv") or {}
        if kv.get("blocks"):
            metrics.decision_kv_pull_blocks.inc(kv["blocks"])
        if kv.get("saved_tokens_est"):
            metrics.decision_kv_tokens_saved.inc(kv["saved_tokens_est"])

    def _export_engine(ledger: dict) -> None:
        spec = ledger.get("spec") or {}
        if spec.get("wasted"):
            metrics.decision_spec_wasted.inc(spec["wasted"])
        if spec.get("flips"):
            metrics.decision_spec_flips.inc(spec["flips"])

    flight.on_finish = _export
    return _export
