"""Request latency attribution: flight timeline → canonical phase ledger.

The flight recorder (obs/events.py) answers "what happened to this request";
this module answers "where did the time GO". At retire, each request's event
timeline is folded into a phase ledger — queue_wait, flow, schedule, retry,
hedge, kv_pull, prefill, decode (serialized) vs decode_overlap (host pack
hidden behind the in-flight device call), chain_stage, spec, preempted,
upstream — whose entries sum to the wall clock **by construction**: every
inter-event interval is attributed to exactly one phase, and anything the
transition maps don't recognize lands in ``unattributed``. The residual is
therefore a real series, not a rounding artifact: a growing unattributed
share means a new latency source the maps don't know about yet (the
"unknown unknown" detector the SLO work keys off).

The ledger is computed from the ``to_dict()`` record shape, so the same
function serves the live exporter (FlightRecorder.on_finish), the
``/debug/requests/<id>`` detail view, and ``tools/dump_flight.py --phases``
against offline dumps.
"""

from __future__ import annotations

from typing import Callable

__all__ = ["PHASES", "build_ledger", "attach_phase_exporter"]

# Canonical phase vocabulary. Keep this list in sync with the
# llmd_tpu:request_phase_seconds label values documented in
# observability/slo-attribution.md.
PHASES = (
    "flow",           # router: parse + flow-control admission bookkeeping
    "queue_wait",     # router flow queue / engine waiting queue
    "schedule",       # scheduler pick / admission → first compute
    "retry",          # router: backoff + re-pick after a failed attempt
    "hedge",          # router: racing a hedged second attempt
    "upstream",       # router: time spent inside the forwarded engine call
    "kv_pull",        # cross-engine prefix pull ahead of admission
    "prefill",        # prompt computation
    "decode",         # serialized decode steps (host pack on the hot path)
    "decode_overlap", # chained decode: host pack hidden behind device call
    "chain_stage",    # dense grammar/bias table staging for a masked chain
    "spec",           # speculative draft + verify steps
    "preempted",      # unscheduled, waiting for re-admission
    "unattributed",   # interval after an event the maps don't know
)

# Events only the router plane emits — their presence selects the router
# transition map (the two planes share "arrival" with different meanings).
_ROUTER_ONLY = {"flow_enqueue", "flow_dispatch", "flow_reject",
                "routing_decision", "kv_pull_stamped", "forward", "response",
                "retry", "hedge", "slo_breach"}

_TERMINAL = {"response", "rejected", "error", "retired", "aborted"}

# state maps: the interval AFTER event X belongs to phase MAP[X].
_ROUTER_MAP = {
    "arrival": "flow",
    "flow_enqueue": "queue_wait",
    "flow_dispatch": "schedule",
    "routing_decision": "schedule",
    "kv_pull_stamped": "schedule",
    "forward": "upstream",
    "retry": "retry",
    "hedge": "upstream",
    "deadline_exceeded": "unattributed",
    "slo_breach": "unattributed",
}

_ENGINE_MAP = {
    "arrival": "queue_wait",
    "structured_compile": "queue_wait",
    "kv_pull": "queue_wait",
    "kv_reload": "schedule",
    "admitted": "schedule",
    "prefill_start": "prefill",
    "prefill_end": "prefill",
    "first_token": "decode",
    "decode": "decode",
    "structured_mask": "decode",
    "chain_dispatch": "decode_overlap",
    "spec_draft": "spec",
    "spec_verify": "spec",
    "preempted": "preempted",
}

# leading interval (record open → first event), keyed by the FIRST event:
# a record opened by the prefix pull attributes its lead-in to kv_pull.
_LEAD_MAP = {
    "kv_pull": "kv_pull",
    "arrival": "flow",  # router parse → arrival stamp (engine overridden below)
}


def _phase_of(event: dict, state_map: dict) -> str:
    name = event.get("event", "")
    phase = state_map.get(name)
    if phase is None:
        return "unattributed"
    if name == "chain_dispatch" and event.get("masked"):
        # masked chains stage dense grammar/bias tables before dispatch —
        # the PR-12 chain_stage cost, distinct from plain pack overlap
        return "chain_stage"
    return phase


def build_ledger(rec: dict) -> dict:
    """Fold one flight record (``to_dict()`` shape) into a phase ledger.

    Returns ``{"plane", "wall_ms", "phases": {phase: ms}, "residual_ms",
    "residual_frac"}``. Invariant: ``sum(phases.values()) + residual_ms ==
    wall_ms`` exactly (up to float noise) — intervals partition the timeline
    and the residual is the tail past the last event plus nothing else.
    """
    events = [e for e in rec.get("events", []) if "t_ms" in e]
    events.sort(key=lambda e: e["t_ms"])
    plane = ("router" if any(e.get("event") in _ROUTER_ONLY for e in events)
             else "engine")
    state_map = _ROUTER_MAP if plane == "router" else _ENGINE_MAP
    wall_ms = float(rec.get("latency_ms") or 0.0)
    phases: dict[str, float] = {}

    def add(phase: str, ms: float) -> None:
        if ms > 0:
            phases[phase] = phases.get(phase, 0.0) + ms

    if events:
        # record open → first event
        first = events[0]
        lead_phase = _LEAD_MAP.get(first.get("event", ""), "unattributed")
        if plane == "engine" and first.get("event") == "arrival":
            lead_phase = "queue_wait"
        add(lead_phase, first["t_ms"])
        # event[i] → event[i+1]
        for prev, nxt in zip(events, events[1:]):
            add(_phase_of(prev, state_map), nxt["t_ms"] - prev["t_ms"])
        # last event → wall clock: for a terminal event this is finish
        # bookkeeping (≈0); for an active record it's the current state
        last = events[-1]
        tail = wall_ms - last["t_ms"]
        if last.get("event") in _TERMINAL:
            residual_ms = max(0.0, tail)
        else:
            add(_phase_of(last, state_map), tail)
            residual_ms = 0.0
    else:
        residual_ms = wall_ms
    # anything that fell into the explicit unattributed phase is residual too:
    # one series for the unknown-unknown detector
    residual_ms += phases.pop("unattributed", 0.0)
    return {
        "plane": plane,
        "wall_ms": round(wall_ms, 3),
        "phases": {k: round(v, 3) for k, v in phases.items()},
        "residual_ms": round(residual_ms, 3),
        "residual_frac": round(residual_ms / wall_ms, 4) if wall_ms > 0 else 0.0,
    }


def attach_phase_exporter(flight, histogram) -> Callable[[dict], None]:
    """Wire a FlightRecorder's ``on_finish`` hook to a
    ``llmd_tpu:request_phase_seconds{phase, tenant, model}`` histogram.

    Every retired request's ledger is exported phase by phase, with the
    residual as its own ``phase="unattributed"`` series. The hook must never
    take down retirement: any failure is swallowed."""

    def _export(rec: dict) -> None:
        try:
            ledger = build_ledger(rec)
            tenant = rec.get("tenant") or "anon"
            model = rec.get("model") or ""
            for phase, ms in ledger["phases"].items():
                histogram.labels(phase=phase, tenant=tenant,
                                 model=model).observe(ms / 1e3)
            histogram.labels(phase="unattributed", tenant=tenant,
                             model=model).observe(ledger["residual_ms"] / 1e3)
        except Exception:
            pass

    flight.on_finish = _export
    return _export
