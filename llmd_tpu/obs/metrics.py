"""Dependency-free Prometheus-style metrics registry.

The reference system leans on the upstream prometheus client; this repo is
a zero-dependency reproduction, so the text-exposition contract
(`# HELP` / `# TYPE` headers, cumulative `_bucket`/`_sum`/`_count`
histogram series, label-value escaping per the Prometheus text format
spec) is implemented here directly.

Design notes:

* Thread-safe. The engine step loop runs on a dedicated thread
  (AsyncLLMEngine) while the aiohttp handlers scrape from the asyncio
  event loop; every mutation and the exposition walk take the registry
  lock.
* Families are idempotent: registering the same (name, type) twice
  returns the existing family, so the engine and its server(s) can share
  one registry without coordination. A type mismatch raises.
* `set_function` attaches a scrape-time callback to an unlabeled
  counter/gauge. This is how legacy counter dicts (scheduler.metrics,
  FlowController.metrics, transfer_stats) surface without dual
  bookkeeping: declare the family once, point it at the dict.
* `Registry.collect()` yields (name, labels, value) samples and
  `Registry.expose()` renders the text format; both servers' `/metrics`
  handlers render through this one code path.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Summary",
    "Registry",
    "escape_label_value",
    "escape_help",
    "EngineMetrics",
    "EngineServerMetrics",
    "RouterMetrics",
    "DeviceMetrics",
    "register_engine_metrics",
    "register_engine_server_metrics",
    "register_router_metrics",
    "register_device_metrics",
]

# Default latency buckets (seconds) — tuned for a TPU serving step loop
# where unified steps land in the 1-500 ms range.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def escape_label_value(value: object) -> str:
    """Escape a label value per the Prometheus text exposition format:
    backslash, double-quote, and newline must be escaped inside the
    double-quoted label value."""
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (but not quotes)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(names: Sequence[str], values: Sequence[object],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{escape_label_value(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v: float) -> str:
    # Integers render without a trailing .0 (matches prometheus_client and
    # keeps byte-for-byte parity with the previous hand-rolled exposition).
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Family:
    """Base class: holds per-label-set children keyed by label values."""

    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: Dict[Tuple[str, ...], object] = {}
        self._fn: Optional[Callable[[], float]] = None
        self._labels_fn: Optional[
            Callable[[], Iterable[Tuple[Dict[str, object], float]]]] = None

    # -- child management -------------------------------------------------
    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def clear(self) -> None:
        """Drop all children (used for scrape-time-refreshed info gauges)."""
        with self._lock:
            self._children.clear()

    def set_function(self, fn: Callable[[], float]) -> None:
        """Attach a scrape-time value callback (unlabeled families only)."""
        if self.labelnames:
            raise ValueError(f"{self.name}: set_function on labeled family")
        self._fn = fn

    def set_labels_function(
            self,
            fn: Callable[[], Iterable[Tuple[Dict[str, object], float]]],
    ) -> None:
        """Attach a scrape-time callback yielding (labels-dict, value) pairs
        for a *labeled* counter/gauge family — the per-device HBM gauges use
        this so the exposed label sets track `jax.local_devices()` without
        the monitor pre-registering a child per device."""
        if not self.labelnames:
            raise ValueError(
                f"{self.name}: set_labels_function on unlabeled family; "
                f"use set_function")
        self._labels_fn = fn

    def _default(self):
        """The implicit child for unlabeled families."""
        if self.labelnames:
            raise ValueError(f"{self.name}: family has labels; use .labels()")
        key = ()
        # llmd-lint: allow[lock-unguarded-read] double-checked fast path: dict get is atomic under the GIL and the miss path re-checks via setdefault under the lock
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _new_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    # -- exposition -------------------------------------------------------
    def samples(self) -> Iterable[Tuple[str, str, float, Optional[tuple]]]:
        """Yield (suffix, rendered-labels, value, exemplar) quads. The
        exemplar slot is None except on histogram bucket series that
        captured one (an (labels-dict, value, unix-ts) triple)."""
        if self._fn is not None:
            yield "", "", float(self._fn()), None
            return
        if self._labels_fn is not None:
            for labels, value in self._labels_fn():
                if set(labels) != set(self.labelnames):
                    continue  # malformed pair: skip rather than corrupt scrape
                key = tuple(str(labels[n]) for n in self.labelnames)
                yield "", _render_labels(self.labelnames, key), float(value), None
            return
        with self._lock:  # snapshot: .labels() can insert mid-scrape
            children = list(self._children.items())
        for key, child in children:
            for s in self._child_samples(key, child):
                yield s if len(s) == 4 else (s[0], s[1], s[2], None)

    def _child_samples(self, key, child):  # pragma: no cover - overridden
        raise NotImplementedError


class _Value:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0.0


class Counter(_Family):
    typ = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        return self._default().value

    def _child_samples(self, key, child):
        yield "", _render_labels(self.labelnames, key), child.value


class _CounterChild:
    def __init__(self, lock):
        self._lock = lock
        self._v = _Value()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._v.v += amount

    @property
    def value(self) -> float:
        return self._v.v


class Gauge(_Family):
    typ = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(-amount)

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        return self._default().value

    def _child_samples(self, key, child):
        yield "", _render_labels(self.labelnames, key), child.value


class _GaugeChild:
    def __init__(self, lock):
        self._lock = lock
        self._v = _Value()

    def set(self, value: float) -> None:
        with self._lock:
            self._v.v = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v.v += amount

    @property
    def value(self) -> float:
        return self._v.v


class Histogram(_Family):
    """Cumulative-bucket histogram: `_bucket{le=...}` series are cumulative
    counts, closed by `le="+Inf"`, plus `_sum` and `_count`.

    OpenMetrics exemplars: ``observe(v, exemplar={"trace_id": ...})`` stores
    the latest exemplar on the bucket ``v`` lands in; exposition appends
    ``# {trace_id="..."} <value> <ts>`` to that bucket line so Grafana can
    jump from a latency bucket straight to the trace."""

    typ = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"{name}: histogram needs at least one bucket")

    def _new_child(self):
        return _HistogramChild(self.buckets, self._lock)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        self._default().observe(value, exemplar=exemplar)

    def _child_samples(self, key, child):
        cum = 0
        for i, (b, c) in enumerate(zip(self.buckets, child.counts)):
            cum += c
            yield ("_bucket",
                   _render_labels(self.labelnames, key, (("le", _fmt(b)),)),
                   cum, child.exemplars[i])
        yield ("_bucket",
               _render_labels(self.labelnames, key, (("le", "+Inf"),)),
               child.count, child.exemplars[len(self.buckets)])
        yield "_sum", _render_labels(self.labelnames, key), child.sum
        yield "_count", _render_labels(self.labelnames, key), child.count


class _HistogramChild:
    def __init__(self, buckets, lock):
        self._buckets = buckets
        self._lock = lock
        self.counts = [0] * len(buckets)
        # latest (labels, value, unix-ts) per bucket, +Inf included
        self.exemplars: List[Optional[tuple]] = [None] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        v = float(value)
        with self._lock:
            self.sum += v
            self.count += 1
            idx = len(self._buckets)  # +Inf unless a finite bucket catches it
            for i, b in enumerate(self._buckets):
                if v <= b:
                    self.counts[i] += 1
                    idx = i
                    break
            if exemplar:
                self.exemplars[idx] = (dict(exemplar), v, time.time())


class Summary(_Family):
    """sum + count only (no quantiles) — enough for rate()-based means."""

    typ = "summary"

    def _new_child(self):
        return _SummaryChild(self._lock)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def sum(self) -> float:
        return self._default().sum

    @property
    def count(self) -> int:
        return self._default().count

    def _child_samples(self, key, child):
        yield "_sum", _render_labels(self.labelnames, key), child.sum
        yield "_count", _render_labels(self.labelnames, key), child.count


class _SummaryChild:
    def __init__(self, lock):
        self._lock = lock
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += float(value)
            self.count += 1


class Registry:
    """A named set of metric families with a single text formatter."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    def _register(self, cls, name, help, labelnames, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"{name}: already registered as {fam.typ}")
                return fam
            fam = cls(name, help, labelnames, self._lock, **kw)
            if not fam.labelnames:
                fam._default()  # expose 0 immediately (contract presence)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def summary(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Summary:
        return self._register(Summary, name, help, labelnames)

    def families(self) -> List[str]:
        """Registered family base names (for the metrics linter)."""
        with self._lock:
            return sorted(self._families)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def collect(self) -> List[Tuple[str, str, float]]:
        """Flat (full_name, rendered_labels, value) sample list."""
        out = []
        with self._lock:
            for name, fam in self._families.items():
                for suffix, labels, value, _ex in fam.samples():
                    out.append((name + suffix, labels, value))
        return out

    def expose(self) -> str:
        """Render the Prometheus text exposition format (with OpenMetrics
        exemplar annotations on histogram buckets that captured one)."""
        lines: List[str] = []
        with self._lock:
            for name, fam in self._families.items():
                if fam.help:
                    lines.append(f"# HELP {name} {escape_help(fam.help)}")
                lines.append(f"# TYPE {name} {fam.typ}")
                for suffix, labels, value, ex in fam.samples():
                    line = f"{name}{suffix}{labels} {_fmt(value)}"
                    if ex is not None:
                        ex_labels, ex_value, ex_ts = ex
                        rendered = ",".join(
                            f'{k}="{escape_label_value(v)}"'
                            for k, v in ex_labels.items())
                        line += (f" # {{{rendered}}} {_fmt(ex_value)}"
                                 f" {ex_ts:.3f}")
                    lines.append(line)
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Family declarations. All static families live here so tools/lint_metrics.py
# can enumerate what the stack emits by building throwaway registries —
# scrape-time callbacks get attached later by the owning component.
# ---------------------------------------------------------------------------


class EngineMetrics:
    """Families owned by LLMEngine (incremented inside the step loop)."""

    def __init__(self, reg: Registry):
        self.registry = reg
        self.step_duration = reg.histogram(
            "llmd_tpu:engine_step_duration_seconds",
            "Engine step wall time by phase "
            "(unified, decode_dispatch, decode_process, spec_verify; pack = "
            "serialized host pack at a chain boundary, pack_overlap = chained "
            "fast-path pack hidden behind the in-flight device call, "
            "chain_stage = dense grammar/bias table staging per chain; attn = "
            "sampled attention-only probe scaled to the fused call: "
            "wall x layers x k; moe_dispatch / moe_experts / moe_combine = "
            "sampled MoE stage probes scaled the same way — the measured DBO "
            "overlap evidence)",
            labelnames=("phase",))
        self.attn_backend_info = reg.gauge(
            "llmd_tpu:engine_attn_backend",
            "Resolved attention backend + active block-size tune-table hash "
            "(info-style: value 1 on the selected label set)",
            labelnames=("backend", "tune"))
        self.batch_occupancy = reg.histogram(
            "llmd_tpu:engine_batch_occupancy",
            "Running/waiting sequence counts sampled once per engine step",
            labelnames=("kind",),
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256))
        self.prefill_tokens = reg.counter(
            "llmd_tpu:prefill_tokens_total",
            "Prompt tokens computed by the engine")
        self.decode_tokens = reg.counter(
            "llmd_tpu:decode_tokens_total",
            "Decode tokens generated by the engine")
        self.preemptions = reg.counter(
            "llmd_tpu:preemptions_total",
            "Sequences preempted (recompute-on-readmit)")
        self.kv_exhaustion = reg.counter(
            "llmd_tpu:kv_block_exhaustion_total",
            "KV page allocations that failed because the pool was exhausted")
        self.requests_waiting = reg.gauge(
            "vllm:num_requests_waiting",
            "Sequences in the engine waiting queue")
        self.requests_running = reg.gauge(
            "vllm:num_requests_running",
            "Sequences actively running in the engine batch")
        self.kv_usage = reg.gauge(
            "vllm:kv_cache_usage_perc",
            "KV cache page utilization (0..1)")
        self.cache_config = reg.gauge(
            "vllm:cache_config_info",
            "Static KV cache configuration",
            labelnames=("block_size", "num_gpu_blocks"))
        self.lora_info = reg.gauge(
            "vllm:lora_requests_info",
            "Running/waiting LoRA adapters (refreshed at scrape time)",
            labelnames=("max_lora", "running_lora_adapters",
                        "waiting_lora_adapters"))
        # KV offload tier: hit/miss/evict incremented inside CPUOffloadStore;
        # saves/loads/demotions/cpu_blocks attach callbacks onto the legacy
        # store counters when offload is enabled.
        self.offload_hits = reg.counter(
            "llmd_tpu:offload_hits_total",
            "CPU offload store lookups that found the block")
        self.offload_misses = reg.counter(
            "llmd_tpu:offload_misses_total",
            "CPU offload store lookups that missed")
        self.offload_evictions = reg.counter(
            "llmd_tpu:offload_evictions_total",
            "Blocks evicted from the CPU offload store (LRU)")
        self.offload_transfer_bytes = reg.histogram(
            "llmd_tpu:offload_transfer_bytes",
            "Bytes moved per offload transfer, by direction (save|load)",
            labelnames=("direction",),
            buckets=(1024, 16384, 65536, 262144, 1048576, 4194304,
                     16777216, 67108864))
        self.offload_saves = reg.counter(
            "llmd_tpu:offload_saves_total",
            "Blocks saved into the CPU offload store")
        self.offload_loads = reg.counter(
            "llmd_tpu:offload_loads_total",
            "Blocks loaded back from the CPU offload store")
        self.offload_demotions = reg.counter(
            "llmd_tpu:offload_demotions_total",
            "Blocks demoted from the CPU store to the filesystem tier")
        self.offload_cpu_blocks = reg.gauge(
            "llmd_tpu:offload_cpu_blocks",
            "Blocks currently resident in the CPU offload store")
        # Prefix-cache effectiveness: fed at admission from
        # seq.num_cached_prompt (engine._try_admit_rank) — the hit data always
        # existed host-side but never reached /metrics.
        self.prefix_cached_tokens = reg.counter(
            "llmd_tpu:engine_prefix_cached_tokens_total",
            "Prompt tokens served from the prefix cache at admission")
        self.prefix_prompt_tokens = reg.counter(
            "llmd_tpu:engine_prefix_prompt_tokens_total",
            "Prompt tokens of admitted sequences (prefix hit-ratio denominator)")
        self.prefix_hit_ratio = reg.gauge(
            "llmd_tpu:engine_prefix_cache_hit_ratio",
            "Cumulative prefix-cache hit ratio (cached / prompt tokens)")
        # Speculative decoding (engine/spec.py prompt-lookup drafts verified
        # through the flat mixed-batch program).
        self.spec_drafted = reg.counter(
            "llmd_tpu:spec_drafted_tokens_total",
            "Draft tokens proposed by the prompt-lookup drafter")
        self.spec_accepted = reg.counter(
            "llmd_tpu:spec_accepted_tokens_total",
            "Draft tokens accepted by greedy verification")
        self.spec_rejected = reg.counter(
            "llmd_tpu:spec_rejected_tokens_total",
            "Draft tokens rejected (rolled back) by greedy verification")
        self.spec_acceptance = reg.summary(
            "llmd_tpu:spec_acceptance_rate",
            "Per-request draft acceptance rate, observed at retirement "
            "(constrained=yes for grammar/logit_bias rows — the spec x "
            "structured compose path)",
            labelnames=("constrained",))
        # Step-program registry (engine/programs.py): per-program dispatch
        # counts; paired with the registry's completion counters they carry
        # the generalized quiesce invariant into /metrics.
        self.program_dispatches = reg.counter(
            "llmd_tpu:engine_program_dispatches_total",
            "Compiled-program dispatches, by step-program registry entry",
            labelnames=("program",))
        # Structured outputs (llmd_tpu/structured): grammar-constrained
        # decoding with on-device logit masks.
        self.structured_requests = reg.counter(
            "llmd_tpu:structured_requests_total",
            "Grammar-constrained requests admitted, by constraint kind",
            labelnames=("kind",))
        self.structured_compile_seconds = reg.histogram(
            "llmd_tpu:structured_compile_seconds",
            "Grammar compile time at admission (cache hits observe ~0)",
            buckets=(0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0))
        self.structured_mask_seconds = reg.histogram(
            "llmd_tpu:structured_mask_build_seconds",
            "Host-side per-step bias build for constrained sample batches",
            buckets=(0.0001, 0.0005, 0.002, 0.01, 0.05, 0.25))
        self.structured_cache_hits = reg.counter(
            "llmd_tpu:structured_cache_hits_total",
            "Compiled-grammar LRU cache hits at admission")
        self.structured_cache_misses = reg.counter(
            "llmd_tpu:structured_cache_misses_total",
            "Compiled-grammar LRU cache misses (fresh compiles) at admission")
        self.structured_violations = reg.counter(
            "llmd_tpu:structured_violations_total",
            "Tokens observed outside the active grammar (incl. truncated "
            "constrained generations counted at retirement)")
        # Latency attribution (obs/attribution.py): each retired request's
        # flight timeline folds into a phase ledger; phases + the
        # unattributed residual sum to wall clock by construction. The same
        # family name is declared by RouterMetrics — registration is
        # idempotent, each plane feeds its own registry.
        self.request_phase = reg.histogram(
            "llmd_tpu:request_phase_seconds",
            "Per-request wall time attributed to a lifecycle phase at "
            "retirement (phase=unattributed is the ledger residual — the "
            "unknown-unknown detector)",
            labelnames=("phase", "tenant", "model"),
            buckets=(0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0))
        # Decision plane, engine view (obs/decisions.py): spec-decode
        # economics folded per request at retirement. The global
        # spec_drafted/spec_accepted counters above tally tokens fleet-wide;
        # these attribute the waste per retired request ledger.
        self.decision_ledgers = reg.counter(
            "llmd_tpu:decision_ledgers_total",
            "Retired requests folded into a decision ledger, by plane "
            "(router | engine; same family declared on both registries)",
            labelnames=("plane",))
        self.decision_spec_wasted = reg.counter(
            "llmd_tpu:decision_spec_wasted_tokens_total",
            "Draft positions packed through verify but rejected, summed per "
            "request at retirement (the speculation lever's wasted compute)")
        self.decision_spec_flips = reg.counter(
            "llmd_tpu:decision_spec_flips_total",
            "Per-sequence drafter arm/disarm transitions summed at "
            "retirement (a high flip rate means the acceptance controller "
            "is thrashing)")
        # Utilization attribution plane (obs/costmodel.py, LLMD_UTIL_LEDGER):
        # analytic per-dispatch FLOPs/bytes joined with measured step walls.
        # The MFU/MBU gauges attach scrape-time callbacks against the device-
        # generation peak table; on CPU (null peaks) the families stay
        # declared but export no samples.
        self.program_mfu = reg.gauge(
            "llmd_tpu:program_mfu",
            "Model FLOPs utilization per step program over the rolling "
            "LLMD_UTIL_WINDOW_S window: analytic dispatched FLOPs / "
            "(window x device peak FLOP/s). Absent when the device "
            "generation has no peak-table entry (e.g. CPU)",
            labelnames=("program",))
        self.program_mbu = reg.gauge(
            "llmd_tpu:program_mbu",
            "HBM bandwidth utilization per step program over the rolling "
            "window: analytic bytes (weight passes + KV page traffic) / "
            "(window x device peak bytes/s). Absent off-device",
            labelnames=("program",))
        self.program_flops = reg.gauge(
            "llmd_tpu:program_flops_per_second",
            "Achieved FLOP/s per step program over the rolling window "
            "(analytic numerator; exported even where peaks are unknown)",
            labelnames=("program",))
        self.program_bytes = reg.gauge(
            "llmd_tpu:program_bytes_per_second",
            "Achieved HBM bytes/s per step program over the rolling window "
            "(analytic numerator; exported even where peaks are unknown)",
            labelnames=("program",))
        self.goodput_tokens = reg.counter(
            "llmd_tpu:goodput_tokens_total",
            "Slot-tokens of every step-program dispatch classified by fate: "
            "committed | spec_rejected | padding | preempted_recompute | "
            "prefix_saved. Per program the kinds partition capacity + saved "
            "tokens, so fractions sum to 1 by construction",
            labelnames=("program", "kind"))
        self.padding_efficiency = reg.gauge(
            "llmd_tpu:program_padding_efficiency",
            "Real packed positions / slot capacity per step program, "
            "cumulative ((0,1]; the standing series for verify's NT "
            "overprovisioning waste)",
            labelnames=("program",))
        self.program_compiles = reg.counter(
            "llmd_tpu:program_compiles_total",
            "XLA compile-cache entries created per step program "
            "(compile_counts() deltas observed at dispatch completion; "
            "growth after warmup = recompile storm)",
            labelnames=("program",))
        self.program_compile_seconds = reg.histogram(
            "llmd_tpu:program_compile_seconds",
            "Step wall observed when a dispatch completion coincided with a "
            "compile-cache miss for its program (compile dominates that "
            "step, so the step wall approximates compile time)",
            labelnames=("program",),
            buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0))
        # MoE dispatch health (ops/moe_dispatch): the legacy einsum path
        # silently drops tokens past moe_capacity_factor — this counter
        # surfaces the quality bug the sorted path eliminates (sorted is
        # drop-free by construction, so path="sorted" staying 0 is the
        # standing invariant; path="einsum" counts routed - kept).
        self.moe_dropped_tokens = reg.counter(
            "llmd_tpu:moe_dropped_tokens_total",
            "Routed MoE tokens dropped at expert capacity, by dispatch path "
            "(sorted is drop-free by construction — a non-zero sorted series "
            "is a dispatch bug; einsum counts routed - kept per step)",
            labelnames=("path",))
        self.moe_ep_imbalance = reg.gauge(
            "llmd_tpu:moe_ep_load_imbalance",
            "Per-EP-rank expert-load imbalance (max/mean routed tokens per "
            "rank over the EPLB window), stamped before and after each "
            "rebalance (when=before|after; 1.0 = perfectly balanced)",
            labelnames=("when",))


class EngineServerMetrics:
    """Families owned by EngineServer (per-frontend in wide-EP mode)."""

    def __init__(self, reg: Registry):
        self.registry = reg
        self.requests = reg.counter(
            "llmd_tpu:requests_total",
            "Generation requests accepted by this frontend")
        self.transfer = {
            key: reg.counter(
                f"llmd_tpu:kv_transfer_{key}_total",
                f"Disaggregated KV transfer: {key}")
            for key in ("exports", "pulls", "notifies", "expired",
                        "injected_blocks", "pull_failures",
                        "prefix_pulls", "prefix_pull_blocks", "released")
        }
        # leak canary for the satellite fix: registrations a dead puller
        # abandoned are released on retire (or reaped on TTL) — a standing
        # non-zero value here under no traffic is a leak
        self.transfer_registrations = reg.gauge(
            "llmd_tpu:kv_transfer_registrations",
            "Live KV export registrations held by the transfer source")
        self.prefix_pull_seconds = reg.histogram(
            "llmd_tpu:kv_transfer_prefix_pull_seconds",
            "Wall time of router-stamped cross-engine prefix pulls, by "
            "outcome (hit|empty|miss|peer_dead|error)",
            labelnames=("outcome",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5))
        # durable prefix tier (kv/writeback.py): flush counter is in BLOCKS
        # (abandoned = queued blocks dropped at the drain-flush deadline);
        # the get counter is in fetch OPS
        self.kv_durable_flush = reg.counter(
            "llmd_tpu:kv_durable_flush_total",
            "Prefix blocks written back to the durable store, by outcome "
            "(ok|error|dropped|abandoned)",
            labelnames=("outcome",))
        self.kv_durable_get = reg.counter(
            "llmd_tpu:kv_durable_get_total",
            "Durable-tier prefix fetches, by outcome "
            "(ok|miss|corrupt|error|breaker_open)",
            labelnames=("outcome",))
        self.kv_durable_queue_depth = reg.gauge(
            "llmd_tpu:kv_durable_queue_depth",
            "Blocks waiting in the write-back flush queue")
        self.kv_durable_breaker = reg.gauge(
            "llmd_tpu:kv_durable_breaker_state",
            "Durable-store circuit breaker (0 closed, 0.5 half-open, 1 open)")


class RouterMetrics:
    """Families owned by RouterServer (EPP-side contract)."""

    def __init__(self, reg: Registry):
        self.registry = reg
        self.requests = reg.counter(
            "llm_d_epp_requests_total", "Requests received by the router")
        self.responses = reg.counter(
            "llm_d_epp_responses_total", "Successful responses")
        self.errors = reg.counter(
            "llm_d_epp_errors_total", "Errored requests")
        self.scheduled = reg.counter(
            "llm_d_epp_scheduled_total", "Scheduling decisions made")
        self.rejected = reg.counter(
            "llm_d_epp_rejected_total", "Requests the scheduler rejected")
        self.pd_splits = reg.counter(
            "llm_d_epp_pd_splits_total", "Prefill/decode disaggregated splits")
        self.pd_aggregated = reg.counter(
            "llm_d_epp_pd_aggregated_total",
            "Disagg decider picks that stayed aggregated (hop skipped)")
        self.flow_enqueued = reg.counter(
            "llm_d_epp_flow_enqueued_total", "Requests admitted to flow queues")
        self.flow_dispatched = reg.counter(
            "llm_d_epp_flow_dispatched_total",
            "Requests dispatched from flow queues")
        self.flow_rejected_capacity = reg.counter(
            "llm_d_epp_flow_rejected_capacity_total",
            "Requests rejected for queue capacity")
        self.flow_evicted_ttl = reg.counter(
            "llm_d_epp_flow_evicted_ttl_total",
            "Queued requests evicted on TTL expiry")
        self.flow_evicted_deadline = reg.counter(
            "llm_d_epp_flow_evicted_deadline_total",
            "Queued requests whose client deadline expired before dispatch")
        self.flow_queue_depth = reg.gauge(
            "llm_d_epp_flow_queue_depth",
            "Requests currently waiting in flow-control queues")
        self.flow_queue_wait = reg.histogram(
            "llm_d_epp_flow_queue_wait_seconds",
            "Enqueue-to-dispatch wait in the flow-control queue",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5, 5.0, 10.0, 30.0))
        self.igw_queue_depth = reg.gauge(
            "igw_queue_depth",
            "External autoscaling signal: queued requests")
        self.igw_running = reg.gauge(
            "igw_running_requests",
            "External autoscaling signal: in-flight requests")
        # histogram (was summary) so the buckets can carry trace exemplars —
        # _sum/_count series are unchanged, rate()-mean queries still work
        self.ttft = reg.histogram(
            "llm_d_epp_ttft_seconds", "Time to first token",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0))
        self.e2e = reg.histogram(
            "llm_d_epp_e2e_seconds", "End-to-end request latency",
            buckets=(0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0))
        # Resilience layer (router/resilience.py, observability/resilience.md)
        self.retries = reg.counter(
            "llm_d_epp_retries_total",
            "Forward attempts retried on an alternate endpoint, by reason",
            labelnames=("reason",))
        self.retries_exhausted = reg.counter(
            "llm_d_epp_retries_exhausted_total",
            "Requests that failed after exhausting every retry attempt")
        self.breaker_opens = reg.counter(
            "llm_d_epp_breaker_opens_total",
            "Per-endpoint circuit breakers tripped open")
        self.breaker_closes = reg.counter(
            "llm_d_epp_breaker_closes_total",
            "Circuit breakers closed after successful half-open probes")
        self.breaker_open_endpoints = reg.gauge(
            "llm_d_epp_breaker_open_endpoints",
            "Endpoints currently ejected by an open circuit breaker")
        self.deadline_exceeded = reg.counter(
            "llm_d_epp_deadline_exceeded_total",
            "Requests rejected 504 because the client budget ran out in the router")
        self.hedges = reg.counter(
            "llm_d_epp_hedges_total",
            "Hedged second attempts fired for short non-streaming requests")
        self.hedge_wins = reg.counter(
            "llm_d_epp_hedge_wins_total",
            "Hedged attempts that answered before the primary")
        self.scrape_errors = reg.counter(
            "llm_d_epp_scrape_errors_total",
            "Endpoint metrics scrapes that failed (passive-health signal)")
        # Global KV plane (llmd_tpu/kvplane, docs/kv-plane.md)
        self.kvplane_precise = reg.counter(
            "llm_d_epp_kv_plane_precise_total",
            "Requests routed on precise event-fed index lookups")
        self.kvplane_degraded = reg.counter(
            "llm_d_epp_kv_plane_degraded_total",
            "Requests degraded to the approx LRU (index cold or feed stale)")
        self.kvplane_lookups = reg.counter(
            "llm_d_epp_kv_plane_lookups_total",
            "Precise index lookups performed by the KV plane")
        self.kvplane_lookup_hits = reg.counter(
            "llm_d_epp_kv_plane_lookup_hits_total",
            "Precise lookups that found at least one indexed block")
        self.kvplane_pulls_stamped = reg.counter(
            "llm_d_epp_kv_plane_pulls_stamped_total",
            "Cross-engine prefix pulls stamped onto forwarded requests")
        self.kvplane_durable_pulls_stamped = reg.counter(
            "llm_d_epp_kv_plane_durable_pulls_stamped_total",
            "Durable-store prefix pulls stamped when no live peer qualified")
        self.kvplane_index_blocks = reg.gauge(
            "llm_d_epp_kv_plane_index_blocks",
            "Block-hash keys resident in the router's KV index")
        self.kvplane_feed_age = reg.gauge(
            "llm_d_epp_kv_plane_feed_age_seconds",
            "Seconds since the KV plane last applied an event batch "
            "(scrape-time; index-staleness alert input)")
        # Latency attribution: router-plane ledger for the same family the
        # engine declares (registration is idempotent; separate registries).
        self.request_phase = reg.histogram(
            "llmd_tpu:request_phase_seconds",
            "Per-request wall time attributed to a lifecycle phase at "
            "retirement (phase=unattributed is the ledger residual — the "
            "unknown-unknown detector)",
            labelnames=("phase", "tenant", "model"),
            buckets=(0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0))
        # Decision plane (obs/decisions.py): why routing chose what it chose
        # and whether the decision paid off, folded at retirement.
        self.decision_ledgers = reg.counter(
            "llmd_tpu:decision_ledgers_total",
            "Retired requests folded into a decision ledger, by plane "
            "(router | engine; same family declared on both registries)",
            labelnames=("plane",))
        self.decision_regret = reg.histogram(
            "llmd_tpu:decision_regret",
            "Chosen-endpoint weighted score minus the best alternative's on "
            "multi-endpoint schedules (<=0; further below zero = the picker "
            "overrode the score order harder), bucketed by whether the "
            "request went on to breach an SLO objective",
            labelnames=("slo_breached",),
            buckets=(-2.0, -1.0, -0.5, -0.2, -0.1, -0.05, -0.02, -0.005,
                     0.0, 0.5))
        self.decision_reschedules = reg.counter(
            "llmd_tpu:decision_reschedules_total",
            "Retry/hedge re-schedules observed on retired request ledgers, "
            "by kind",
            labelnames=("kind",))
        self.predictor_calibration_error = reg.histogram(
            "llmd_tpu:predictor_calibration_error_ms",
            "Signed latency-predictor calibration error (observed minus "
            "predicted, ms) joined at retirement, per objective (ttft|e2e) "
            "and model — a skewed sign means systematic bias, wide spread "
            "means the predictor is noise",
            labelnames=("objective", "model"),
            buckets=(-5000.0, -1000.0, -250.0, -50.0, -10.0, 0.0, 10.0,
                     50.0, 250.0, 1000.0, 5000.0))
        self.predictor_calibration_ape = reg.gauge(
            "llmd_tpu:predictor_calibration_ape",
            "Rolling mean absolute percentage error of the latency "
            "predictor over the last LLMD_DECISION_CALIB_WINDOW retired "
            "requests, per objective and model",
            labelnames=("objective", "model"))
        self.decision_kv_pull_blocks = reg.counter(
            "llmd_tpu:decision_kv_pull_blocks_total",
            "KV blocks covered by router-stamped cross-engine pulls, summed "
            "over retired request ledgers")
        self.decision_kv_tokens_saved = reg.counter(
            "llmd_tpu:decision_kv_tokens_saved_total",
            "Estimated re-prefill tokens saved by stamped pulls (plan-time "
            "estimate: peer prefix beyond the chosen target's), summed over "
            "retired request ledgers — weigh against "
            "llmd_tpu:kv_transfer_prefix_pull_seconds actually spent")
        # Per-tenant accounting (x-llm-d-tenant, default "anon"): the
        # fairness foundation — token spend and request volume by tenant.
        self.tenant_requests = reg.counter(
            "llm_d_epp_tenant_requests_total",
            "Requests received, by tenant and model",
            labelnames=("tenant", "model"))
        self.tenant_prompt_tokens = reg.counter(
            "llm_d_epp_tenant_prompt_tokens_total",
            "Prompt tokens consumed, by tenant and model (from upstream "
            "usage accounting)",
            labelnames=("tenant", "model"))
        self.tenant_completion_tokens = reg.counter(
            "llm_d_epp_tenant_completion_tokens_total",
            "Completion tokens generated, by tenant and model",
            labelnames=("tenant", "model"))
        # SLO objectives + burn rate (obs/slo.py, LLMD_SLO_*): attainment and
        # burn gauges are scrape-time callbacks over the rolling windows.
        self.slo_attainment = reg.gauge(
            "llm_d_epp_slo_attainment",
            "Rolling fraction of requests meeting the objective, per tenant "
            "x objective (ttft|e2e) x window (5m|1h)",
            labelnames=("tenant", "objective", "window"))
        self.slo_burn_rate = reg.gauge(
            "llm_d_epp_slo_burn_rate",
            "Error-budget burn rate: (1 - attainment) / (1 - target); 1.0 "
            "burns the budget exactly at the objective rate",
            labelnames=("tenant", "objective", "window"))
        self.slo_breaches = reg.counter(
            "llm_d_epp_slo_breaches_total",
            "Individual requests that missed their objective",
            labelnames=("tenant", "objective"))
        # Fleet rollup plane (obs/fleet.py): aggregated over MetricsPoller
        # scrapes so ONE router scrape answers fleet health — the pool
        # controller and dashboards consume these instead of re-deriving
        # fleet state from per-replica series.
        self.fleet_replicas = reg.gauge(
            "llmd_tpu:fleet_replicas",
            "Replicas currently contributing to the fleet rollup")
        self.fleet_tokens_per_second = reg.gauge(
            "llmd_tpu:fleet_tokens_per_second",
            "Fleet-wide generation throughput from scrape-to-scrape decode "
            "token-counter deltas")
        self.fleet_running = reg.gauge(
            "llmd_tpu:fleet_running_requests",
            "Sum of running sequences across the fleet")
        self.fleet_waiting = reg.gauge(
            "llmd_tpu:fleet_waiting_requests",
            "Sum of queued sequences across the fleet")
        self.fleet_hbm_headroom_min = reg.gauge(
            "llmd_tpu:fleet_hbm_headroom_bytes_min",
            "Smallest per-replica HBM headroom (limit - in-use, summed over "
            "the replica's devices) — the next-OOM candidate")
        self.fleet_hbm_headroom_total = reg.gauge(
            "llmd_tpu:fleet_hbm_headroom_bytes_total",
            "Total HBM headroom across the fleet")
        self.fleet_kv_utilization = reg.gauge(
            "llmd_tpu:fleet_kv_utilization_mean",
            "Mean KV cache utilization across replicas (0..1)")
        self.fleet_fabric_alive = reg.gauge(
            "llmd_tpu:fleet_fabric_alive_replicas",
            "Replicas whose device fabric liveness probe is passing")
        self.fleet_stalled = reg.gauge(
            "llmd_tpu:fleet_stalled_replicas",
            "Replicas whose step watchdog currently reports a stall")
        self.fleet_goodput_ratio = reg.gauge(
            "llmd_tpu:fleet_goodput_committed_ratio",
            "Fleet-wide committed fraction of classified slot-tokens from "
            "scrape-to-scrape goodput-counter deltas (weighted by tokens; "
            "the one-number answer to how much dispatched compute became "
            "output)")
        self.fleet_mfu = reg.gauge(
            "llmd_tpu:fleet_mfu_mean",
            "Mean of per-program MFU samples across replicas exporting them "
            "(absent while no replica runs on a peak-table device)")


class PoolMetricsFamilies:
    """Families owned by the pool controller (llmd_tpu/pool/controller.py)."""

    def __init__(self, reg: Registry):
        self.registry = reg
        self.desired_replicas = reg.gauge(
            "llmd_tpu:pool_desired_replicas",
            "Replica count the autoscaling policy currently wants")
        self.ready_replicas = reg.gauge(
            "llmd_tpu:pool_ready_replicas",
            "Replicas launched, ready, and registered with router discovery")
        self.scale_decisions = reg.counter(
            "llmd_tpu:pool_scale_decisions_total",
            "Reconcile decisions that changed the replica count, by reason",
            labelnames=("reason",))
        self.warm_start = reg.histogram(
            "llmd_tpu:pool_warm_start_seconds",
            "Replica launch-to-ready duration by kind (cold = full engine "
            "build, warm = snapshot restore)",
            labelnames=("kind",),
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0))


class DeviceMetrics:
    """Families owned by DeviceMonitor (llmd_tpu/obs/device.py): HBM
    telemetry, fabric liveness, the step watchdog, and profiler captures."""

    def __init__(self, reg: Registry):
        self.registry = reg
        self.hbm_bytes_in_use = reg.gauge(
            "llmd_tpu:device_hbm_bytes_in_use",
            "HBM bytes currently allocated, per device "
            "(absent on backends without memory_stats, e.g. CPU)",
            labelnames=("device",))
        self.hbm_peak_bytes = reg.gauge(
            "llmd_tpu:device_hbm_peak_bytes",
            "Peak HBM bytes allocated since process start, per device",
            labelnames=("device",))
        self.hbm_limit_bytes = reg.gauge(
            "llmd_tpu:device_hbm_limit_bytes",
            "HBM allocation limit, per device",
            labelnames=("device",))
        self.fabric_alive = reg.gauge(
            "llmd_tpu:device_fabric_alive",
            "1 while the fabric liveness probe completes within its timeout, "
            "0 once a probe wedges or fails")
        self.fabric_probe_failures = reg.counter(
            "llmd_tpu:device_fabric_probe_failures_total",
            "Fabric liveness probes that timed out or raised")
        self.fabric_probe_seconds = reg.histogram(
            "llmd_tpu:device_fabric_probe_seconds",
            "Wall time of successful fabric liveness probes",
            buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 30.0))
        self.engine_stalled = reg.gauge(
            "llmd_tpu:engine_stalled",
            "1 while the step watchdog sees pending work with no dispatch-"
            "loop heartbeat for LLMD_WATCHDOG_STALL_S, else 0")
        self.engine_stalls = reg.counter(
            "llmd_tpu:engine_stalls_total",
            "Stall episodes detected by the step watchdog")
        self.heartbeat_age = reg.gauge(
            "llmd_tpu:engine_heartbeat_age_seconds",
            "Seconds since the engine dispatch loop last stamped its "
            "heartbeat (scrape-time)")
        self.profile_captures = reg.counter(
            "llmd_tpu:profile_captures_total",
            "On-demand jax.profiler windows captured via /debug/profile")


def register_engine_metrics(reg: Registry) -> EngineMetrics:
    return EngineMetrics(reg)


def register_engine_server_metrics(reg: Registry) -> EngineServerMetrics:
    return EngineServerMetrics(reg)


def register_router_metrics(reg: Registry) -> RouterMetrics:
    return RouterMetrics(reg)


def register_pool_metrics(reg: Registry) -> PoolMetricsFamilies:
    return PoolMetricsFamilies(reg)


def register_device_metrics(reg: Registry) -> DeviceMetrics:
    return DeviceMetrics(reg)
