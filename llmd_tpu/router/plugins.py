"""Scheduler plugin framework: extension points + registry.

Parity: reference epp/scheduling.md:50-68 — extension points ProfilePicker, Filter,
Scorer, Picker, ProcessResults; request-handling.md:50-86 — Parser, DataProducer,
Admitter with auto-wired hooks (PreRequest, ResponseHeaderProcessor,
ResponseBodyProcessor). Plugin instances are declared in the config graph
(core/config.FrameworkConfig) by `type` and wired by `name`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Protocol, runtime_checkable

from llmd_tpu.core.endpoint import Endpoint
from llmd_tpu.core.request import InferenceRequest


@runtime_checkable
class Filter(Protocol):
    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]: ...


@runtime_checkable
class Scorer(Protocol):
    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]: ...


@runtime_checkable
class Picker(Protocol):
    def pick(self, req: InferenceRequest, scored: dict[Endpoint, float]) -> Optional[Endpoint]: ...


class DataProducer:
    """Per-request state producer with lifecycle hooks (request-handling.md:81-86)."""

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None: ...

    def pre_request(self, req: InferenceRequest, endpoint: Endpoint) -> None: ...

    def post_response(self, req: InferenceRequest, endpoint: Endpoint,
                      response_info: dict[str, Any]) -> None: ...


class Admitter:
    """Admission gate evaluated after producers, before scheduling."""

    def admit(self, req: InferenceRequest, endpoints: list[Endpoint]) -> tuple[bool, str]:
        return True, ""


PLUGIN_REGISTRY: dict[str, Callable[..., Any]] = {}


def register_plugin(type_name: str):
    def deco(cls):
        PLUGIN_REGISTRY[type_name] = cls
        cls.plugin_type = type_name
        return cls

    return deco


def build_plugin(type_name: str, params: dict[str, Any], ctx: Optional[dict[str, Any]] = None):
    """Instantiate a plugin type with its config params (+ optional shared context).

    Plugins that need shared services (prefix index, predictor client) declare
    `needs_ctx = True` and receive the context dict as first arg.
    """
    cls = PLUGIN_REGISTRY.get(type_name)
    if cls is None:
        raise KeyError(f"unknown plugin type {type_name!r}; known: {sorted(PLUGIN_REGISTRY)}")
    if getattr(cls, "needs_ctx", False):
        # NOT `ctx or {}`: the shared context is an EMPTY dict at construction
        # time, which is falsy — that would hand every plugin a private fresh
        # dict and silently break all cross-component ctx sharing (the KV-event
        # subscriber feeding an index no scorer reads, inflight counts no
        # flow-controller sees).
        return cls(ctx if ctx is not None else {}, **params)
    return cls(**params)


def known_plugin_types() -> set[str]:
    return set(PLUGIN_REGISTRY)
