"""Endpoint discovery sources feeding the EndpointPool.

Parity: the reference data layer's endpoint sources
(/root/reference/docs/architecture/core/router/epp/datalayer.md:5-91) —
``k8s-notification-source`` (GVK watch keyed by the InferencePool selector;
pods join at status Running, leave on deletion) and the ``file-discovery``
plugin of no-Kubernetes mode
(guides/no-kubernetes-deployment/router/epp/config.yaml:10-41). Both implement
one ``EndpointSource`` interface over the same ``EndpointPool``, so the
scheduler never knows which discovery mode is running.

``K8sWatchSource`` speaks the plain Kubernetes HTTP API (list + watch with
resourceVersion resume, bookmark tolerance, backoff re-list) via aiohttp — no
kubernetes client dependency; in-cluster config comes from the conventional
service-account mount. The fixture-tested contract lives in
tests/test_discovery.py against a fake API server.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Optional

import aiohttp

log = logging.getLogger("llmd_tpu.discovery")

from llmd_tpu.core.endpoint import Endpoint, EndpointPool, EndpointRole
from llmd_tpu.router.datalayer import load_endpoints_file

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class EndpointSource:
    """Discovery source interface: populate/maintain an EndpointPool."""

    def __init__(self, pool: EndpointPool) -> None:
        self.pool = pool

    async def start(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    async def stop(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class FileSource(EndpointSource):
    """file-discovery with live re-scan: edits to the endpoints file (add /
    remove lines) apply without a restart (mtime-polled)."""

    def __init__(self, pool: EndpointPool, path: str,
                 rescan_interval_s: float = 2.0) -> None:
        super().__init__(pool)
        self.path = path
        self.interval = rescan_interval_s
        self._task: Optional[asyncio.Task] = None
        self._mtime = 0.0
        self._known: set[str] = set()
        self.last_error: Optional[Exception] = None

    def _scan(self) -> None:
        staging = EndpointPool()
        load_endpoints_file(staging, self.path)
        now = {e.address for e in staging.list()}
        for e in staging.list():
            self.pool.upsert(e)
        for gone in self._known - now:
            self.pool.remove(gone)
        self._known = now

    async def start(self) -> None:
        self._scan()
        self._mtime = os.path.getmtime(self.path)
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                m = os.path.getmtime(self.path)
                if m != self._mtime:
                    self._mtime = m
                    self._scan()
                    self.last_error = None
            except OSError:
                pass  # file briefly absent mid-rewrite
            except Exception as e:  # malformed content must not kill live reload
                if str(e) != str(self.last_error or ""):
                    log.warning("endpoints file %s re-scan failed: %s", self.path, e)
                self.last_error = e

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


def _pod_to_endpoint(pod: dict, port: int) -> Optional[Endpoint]:
    """Running+ready pod → Endpoint; None when it should not be routed."""
    status = pod.get("status", {})
    if status.get("phase") != "Running" or not status.get("podIP"):
        return None
    conds = {c.get("type"): c.get("status") for c in status.get("conditions", [])}
    if conds.get("Ready") != "True":
        return None
    labels = pod.get("metadata", {}).get("labels", {})
    role = labels.get("llm-d.ai/role", "both")
    try:
        role_e = EndpointRole(role)
    except ValueError:
        role_e = EndpointRole.BOTH
    return Endpoint(
        address=f"{status['podIP']}:{port}",
        name=pod.get("metadata", {}).get("name", ""),
        role=role_e,
        labels=labels,
        engine_type=labels.get("llm-d.ai/engine-type", "llmd-tpu"),
    )


class K8sWatchSource(EndpointSource):
    """Kubernetes pod watch keyed by the InferencePool's selector.

    list → seed pool (+resourceVersion) → watch stream (ADDED/MODIFIED map to
    upsert-or-remove on readiness, DELETED removes); 410 Gone / stream end →
    re-list with backoff. Multi-port pools (DP rank engines,
    inferencepool.md targetPorts ≤ 8) surface one endpoint per podIP:port.
    """

    def __init__(
        self,
        pool: EndpointPool,
        selector: dict[str, str],
        ports: list[int],
        namespace: str = "default",
        api_base: Optional[str] = None,
        token: Optional[str] = None,
        rebackoff_s: float = 1.0,
    ) -> None:
        super().__init__(pool)
        self.selector = selector
        self.ports = ports[:8]  # targetPorts limit (inferencepool.md)
        self.namespace = namespace
        self.api_base = api_base or self._in_cluster_base()
        self.token = token if token is not None else self._in_cluster_token()
        self.backoff = rebackoff_s
        self._task: Optional[asyncio.Task] = None
        self._addresses: dict[str, set[str]] = {}  # pod uid → addresses
        self.relists = 0
        self.events_seen = 0
        self.last_error: Optional[Exception] = None

    @staticmethod
    def _in_cluster_base() -> str:
        # the in-cluster API server is always TLS regardless of port
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return f"https://{host}:{port}"

    @staticmethod
    def _in_cluster_token() -> Optional[str]:
        try:
            with open(os.path.join(SA_DIR, "token")) as f:
                return f.read().strip()
        except OSError:
            return None

    @property
    def _label_selector(self) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(self.selector.items()))

    def _headers(self) -> dict[str, str]:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def _apply(self, pod: dict, deleted: bool) -> None:
        uid = pod.get("metadata", {}).get("uid") or pod.get("metadata", {}).get("name", "")
        old = self._addresses.pop(uid, set())
        new: set[str] = set()
        if not deleted:
            for port in self.ports:
                ep = _pod_to_endpoint(pod, port)
                if ep is not None:
                    self.pool.upsert(ep)
                    new.add(ep.address)
        for addr in old - new:
            self.pool.remove(addr)
        if new:
            self._addresses[uid] = new

    async def _list(self, session: aiohttp.ClientSession) -> str:
        url = (f"{self.api_base}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector={self._label_selector}")
        async with session.get(url, headers=self._headers()) as resp:
            resp.raise_for_status()
            data = await resp.json()
        self.relists += 1
        seen_uids = set()
        for pod in data.get("items", []):
            self._apply(pod, deleted=False)
            meta = pod.get("metadata", {})
            # same key fallback as _apply, or uid-less pods would be swept
            seen_uids.add(meta.get("uid") or meta.get("name", ""))
        for uid in list(self._addresses):
            if uid not in seen_uids:
                self._apply({"metadata": {"uid": uid}}, deleted=True)
        return data.get("metadata", {}).get("resourceVersion", "")

    async def _watch(self, session: aiohttp.ClientSession, rv: str) -> None:
        url = (f"{self.api_base}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector={self._label_selector}&watch=1&resourceVersion={rv}"
               f"&allowWatchBookmarks=true")
        async with session.get(
            url, headers=self._headers(),
            timeout=aiohttp.ClientTimeout(total=None, sock_read=330),
        ) as resp:
            resp.raise_for_status()
            async for line in resp.content:
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                self.events_seen += 1
                etype = ev.get("type")
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":  # e.g. 410 Gone — caller re-lists
                    return
                self._apply(ev.get("object", {}), deleted=etype == "DELETED")

    async def _loop(self) -> None:
        connector = None
        if self.api_base.startswith("https") and os.path.isfile(
                os.path.join(SA_DIR, "ca.crt")):
            import ssl

            ctx = ssl.create_default_context(cafile=os.path.join(SA_DIR, "ca.crt"))
            connector = aiohttp.TCPConnector(ssl=ctx)
        # read_bufsize: watch events are one JSON line per pod object — real pods
        # routinely exceed aiohttp's 64 KiB default line limit (managedFields)
        async with aiohttp.ClientSession(connector=connector,
                                         read_bufsize=4 * 1024 * 1024) as session:
            while True:
                try:
                    rv = await self._list(session)
                    self.last_error = None
                    await self._watch(session, rv)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # API hiccup → backoff + full re-list
                    if str(e) != str(self.last_error or ""):
                        log.warning("k8s pod watch (%s ns=%s): %s — re-listing "
                                    "every %.1fs", self._label_selector,
                                    self.namespace, e, self.backoff)
                    self.last_error = e
                await asyncio.sleep(self.backoff)

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
