"""Router / EPP-equivalent: the scheduling brain of the stack.

Re-implements the reference's Endpoint Picker (docs/architecture/core/router/epp/):
request parsing → flow control → Filter→Score→Pick scheduling → endpoint choice,
with the data layer feeding per-endpoint metrics and the KV plane feeding prefix
affinity. Runs standalone (built-in HTTP proxy, file-discovery) — the analogue of the
reference's no-Kubernetes mode (guides/no-kubernetes-deployment/) — with the same
plugin-config surface so k8s-mode wiring is config, not code.
"""

from llmd_tpu.router.plugins import (  # noqa: F401
    PLUGIN_REGISTRY,
    Filter,
    Picker,
    Scorer,
    DataProducer,
    Admitter,
    register_plugin,
    build_plugin,
)
from llmd_tpu.router.scheduler import Scheduler, SchedulingResult  # noqa: F401

# register plugin suites (import side effect populates PLUGIN_REGISTRY)
from llmd_tpu.router import filters_pickers, latency_plugins, scorers  # noqa: E402,F401
from llmd_tpu.kv import plugins as _kv_plugins  # noqa: E402,F401
