"""Flow control: pool defense via priority bands, fairness, ordering, saturation.

Parity: reference epp/flow-control.md —
- FlowKey = (FairnessID, Priority), 3-tier dispatch Priority→Fairness→Ordering
  (:25-44), band capacity maxBytes/maxRequests, TTL eviction,
- FairnessPolicy: round-robin | global-strict; OrderingPolicy: fcfs | edf |
  slo-deadline (:242-254),
- SaturationDetector gates the dispatch loop (utilization-detector default,
  concurrency-detector) (:293-344),
- queues are in-memory only, lost on crash (:354); outcome → HTTP mapping lives in
  core.request.RequestOutcome (429/503/500).
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from llmd_tpu.core.config import FlowControlSpec, PriorityBandSpec
from llmd_tpu.core.endpoint import EndpointPool
from llmd_tpu.core.metrics_contract import StdMetric
from llmd_tpu.core.request import InferenceRequest, RequestOutcome


@dataclass
class QueuedItem:
    req: InferenceRequest
    enqueue_time: float
    future: asyncio.Future  # resolves to RequestOutcome
    byte_size: int

    def deadline(self) -> float:
        """EDF deadline: SLO-TTFT if present, else arrival+TTL ordering proxy."""
        if self.req.slo_ttft_ms is not None:
            return self.req.arrival_time + self.req.slo_ttft_ms / 1000.0
        return self.enqueue_time + 3600.0


class SaturationDetector:
    def saturated(self, pool: EndpointPool) -> bool:
        raise NotImplementedError


class UtilizationDetector(SaturationDetector):
    """Saturated when every endpoint is above kv-util or queue thresholds
    (flow-control.md utilization-detector defaults)."""

    def __init__(self, kv_threshold: float = 0.95, queue_threshold: int = 5) -> None:
        self.kv_threshold = kv_threshold
        self.queue_threshold = queue_threshold

    def saturated(self, pool: EndpointPool) -> bool:
        eps = pool.list()
        if not eps:
            return True
        return all(
            e.metric(StdMetric.KV_UTILIZATION) >= self.kv_threshold
            or e.metric(StdMetric.QUEUED_REQUESTS) >= self.queue_threshold
            for e in eps
        )


class ConcurrencyDetector(SaturationDetector):
    def __init__(self, max_inflight_per_endpoint: int = 64,
                 inflight: Optional[dict[str, int]] = None) -> None:
        self.limit = max_inflight_per_endpoint
        self.inflight = inflight if inflight is not None else {}

    def saturated(self, pool: EndpointPool) -> bool:
        eps = pool.list()
        if not eps:
            return True
        return all(self.inflight.get(e.address, 0) >= self.limit for e in eps)


DETECTORS: dict[str, Callable[..., SaturationDetector]] = {
    "utilization-detector": UtilizationDetector,
    "concurrency-detector": ConcurrencyDetector,
}


class PriorityBand:
    """One priority level: per-fairness-id flow queues + fairness + ordering policy."""

    def __init__(self, spec: PriorityBandSpec) -> None:
        self.spec = spec
        self.flows: OrderedDict[str, deque[QueuedItem]] = OrderedDict()
        self.bytes = 0
        self.count = 0

    def over_capacity(self, item_bytes: int) -> bool:
        return (self.count + 1 > self.spec.max_requests
                or self.bytes + item_bytes > self.spec.max_bytes)

    def push(self, item: QueuedItem) -> None:
        fid = item.req.fairness_id
        q = self.flows.get(fid)
        if q is None:
            q = self.flows[fid] = deque()
        q.append(item)
        self.bytes += item.byte_size
        self.count += 1

    def _order_key(self, item: QueuedItem) -> float:
        if self.spec.ordering_policy == "fcfs":
            return item.enqueue_time
        if self.spec.ordering_policy in ("edf", "slo-deadline"):
            return item.deadline()
        return item.enqueue_time

    def pop(self) -> Optional[QueuedItem]:
        """Fairness across flows, ordering within the chosen flow."""
        while self.flows:
            if self.spec.fairness_policy == "global-strict":
                # globally best item across all flows by ordering key
                best_fid, best_item = None, None
                for fid, q in self.flows.items():
                    if not q:
                        continue
                    cand = min(q, key=self._order_key)
                    if best_item is None or self._order_key(cand) < self._order_key(best_item):
                        best_fid, best_item = fid, cand
                if best_item is None:
                    return None
                self.flows[best_fid].remove(best_item)
                if not self.flows[best_fid]:
                    del self.flows[best_fid]
                item = best_item
            else:  # round-robin over flows
                fid, q = next(iter(self.flows.items()))
                self.flows.move_to_end(fid)
                if not q:
                    del self.flows[fid]
                    continue
                item = min(q, key=self._order_key) if self.spec.ordering_policy != "fcfs" else q[0]
                q.remove(item)
                if not q:
                    del self.flows[fid]
            self.bytes -= item.byte_size
            self.count -= 1
            return item
        return None

    def evict_expired(self, now: float) -> list[tuple[QueuedItem, str]]:
        """Drop TTL-expired and deadline-expired items; returns (item, why)
        with why in {"ttl", "deadline"}. A request whose client budget ran
        out while queued must NOT dispatch with a stale budget — it gets a
        504 here instead of timing out downstream after wasting an endpoint."""
        out: list[tuple[QueuedItem, str]] = []
        for fid in list(self.flows):
            q = self.flows[fid]
            keep: deque[QueuedItem] = deque()
            for item in q:
                dl = item.req.deadline()
                if dl is not None and now >= dl:
                    out.append((item, "deadline"))
                elif now - item.enqueue_time > self.spec.ttl_s:
                    out.append((item, "ttl"))
                else:
                    keep.append(item)
                    continue
                self.bytes -= item.byte_size
                self.count -= 1
            if keep:
                self.flows[fid] = keep
            else:
                del self.flows[fid]
        return out


class FlowController:
    """EnqueueAndWait front + saturation-gated dispatch worker (flow-control.md:258-295)."""

    def __init__(self, spec: FlowControlSpec, pool: EndpointPool,
                 ctx: Optional[dict[str, Any]] = None) -> None:
        self.spec = spec
        self.pool = pool
        if not spec.bands:
            spec.bands = [PriorityBandSpec(priority=0, name="default")]
        # higher priority value = more important; dispatch highest first
        self.bands: dict[int, PriorityBand] = {
            b.priority: PriorityBand(b) for b in spec.bands
        }
        det_cls = DETECTORS.get(spec.saturation_detector, UtilizationDetector)
        if spec.saturation_detector == "concurrency-detector":
            self.detector = det_cls(inflight=(ctx or {}).get("inflight_requests"))
        else:
            self.detector = det_cls()
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.metrics = {
            "enqueued_total": 0, "dispatched_total": 0, "rejected_capacity_total": 0,
            "evicted_ttl_total": 0, "evicted_deadline_total": 0, "queue_depth": 0,
        }
        # obs.metrics Histogram observing enqueue→dispatch wait; attached by
        # the router (llm_d_epp_flow_queue_wait_seconds), None standalone
        self.queue_wait_histogram = None
        # obs.events FlightRecorder; attached by the router, None standalone
        self.flight = None
        self._shutdown = False

    # -- API ---------------------------------------------------------------
    async def enqueue_and_wait(self, req: InferenceRequest) -> RequestOutcome:
        rem = req.remaining_s()
        if rem is not None and rem <= 0:
            # budget already spent before queueing (tiny client timeout or a
            # slow parse): don't occupy queue capacity just to evict it later
            self.metrics["evicted_deadline_total"] += 1
            if self.flight is not None:
                self.flight.record(req.request_id, "deadline_exceeded",
                                   where="flow_enqueue")
            return RequestOutcome.EVICTED_DEADLINE
        band = self.bands.get(req.priority)
        if band is None:
            # snap to nearest lower band, else lowest
            lower = [p for p in self.bands if p <= req.priority]
            band = self.bands[max(lower)] if lower else self.bands[min(self.bands)]
        size = req.byte_size or 1024
        if band.over_capacity(size):
            self.metrics["rejected_capacity_total"] += 1
            if self.flight is not None:
                self.flight.record(req.request_id, "flow_reject",
                                   reason="capacity", band=band.spec.name)
            return RequestOutcome.REJECTED_CAPACITY
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        band.push(QueuedItem(req=req, enqueue_time=time.monotonic(), future=fut, byte_size=size))
        self.metrics["enqueued_total"] += 1
        if self.flight is not None:
            self.flight.record(req.request_id, "flow_enqueue",
                               priority=req.priority, band=band.spec.name,
                               queue_depth=self._total_queued(),
                               tenant=req.tenant or None)
        self._wake.set()
        return await fut

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._dispatch_loop())

    async def stop(self) -> None:
        self._shutdown = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for band in self.bands.values():
            while (item := band.pop()) is not None:
                if not item.future.done():
                    item.future.set_result(RequestOutcome.EVICTED_SHUTDOWN)

    # -- worker ------------------------------------------------------------
    def _total_queued(self) -> int:
        return sum(b.count for b in self.bands.values())

    async def _dispatch_loop(self) -> None:
        while True:
            if self._total_queued() == 0:
                self._wake.clear()
                await self._wake.wait()
            now = time.monotonic()
            for band in self.bands.values():
                for item, why in band.evict_expired(now):
                    if why == "deadline":
                        self.metrics["evicted_deadline_total"] += 1
                        if self.flight is not None:
                            self.flight.record(
                                item.req.request_id, "deadline_exceeded",
                                where="flow_control",
                                waited_ms=round((now - item.enqueue_time) * 1e3, 3))
                        if not item.future.done():
                            item.future.set_result(RequestOutcome.EVICTED_DEADLINE)
                        continue
                    self.metrics["evicted_ttl_total"] += 1
                    if self.flight is not None:
                        self.flight.record(
                            item.req.request_id, "flow_reject", reason="ttl",
                            waited_ms=round((now - item.enqueue_time) * 1e3, 3))
                    if not item.future.done():
                        item.future.set_result(RequestOutcome.EVICTED_TTL)
            if self.detector.saturated(self.pool):
                await asyncio.sleep(0.01)  # hold dispatch while pool is saturated
                continue
            item = None
            for prio in sorted(self.bands, reverse=True):
                item = self.bands[prio].pop()
                if item is not None:
                    break
            if item is None:
                continue
            self.metrics["dispatched_total"] += 1
            self.metrics["queue_depth"] = self._total_queued()
            if self.queue_wait_histogram is not None:
                self.queue_wait_histogram.observe(
                    time.monotonic() - item.enqueue_time)
            if self.flight is not None:
                self.flight.record(
                    item.req.request_id, "flow_dispatch",
                    wait_ms=round((time.monotonic() - item.enqueue_time) * 1e3, 3))
            if not item.future.done():
                item.future.set_result(RequestOutcome.DISPATCHED)
            await asyncio.sleep(0)  # yield so dispatched request can start
