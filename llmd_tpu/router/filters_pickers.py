"""Filter and Picker plugins (reference epp/scheduling.md:77-83, 104-108)."""

from __future__ import annotations

import random
from typing import Any, Optional

from llmd_tpu.core.endpoint import Endpoint, EndpointRole
from llmd_tpu.core.metrics_contract import StdMetric
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.router.plugins import register_plugin
from llmd_tpu.router.scorers import STATE_PREFIX_HITS


@register_plugin("label-selector-filter")
class LabelSelectorFilter:
    def __init__(self, **labels: str) -> None:
        self.labels = labels

    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]:
        return [
            e for e in endpoints
            if all(e.labels.get(k) == v for k, v in self.labels.items())
        ]


@register_plugin("prefill-endpoints-filter")
class PrefillEndpointsFilter:
    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]:
        return [e for e in endpoints if e.role in (EndpointRole.PREFILL, EndpointRole.BOTH)]


@register_plugin("decode-endpoints-filter")
class DecodeEndpointsFilter:
    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]:
        return [e for e in endpoints if e.role in (EndpointRole.DECODE, EndpointRole.BOTH)]


@register_plugin("prefix-cache-affinity-filter")
class PrefixCacheAffinityFilter:
    """Epsilon-greedy prefix affinity with load gates (latency-predictor.md:110-115):
    exploit cache-warm endpoints, explore with probability epsilon, and break
    affinity when the warm pods are materially slower — by queue depth always, and
    by predicted TTFT when the latency producer has run (the TTFT load gate)."""

    def __init__(self, epsilon: float = 0.05, queue_gate: float = 16.0,
                 ttft_penalty_ms: float = 500.0) -> None:
        self.epsilon = epsilon
        self.queue_gate = queue_gate
        self.ttft_penalty_ms = ttft_penalty_ms

    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]:
        hits = req.state.get(STATE_PREFIX_HITS) or {}
        if not hits or random.random() < self.epsilon:
            return endpoints
        best = max(hits.values())
        if best <= 0:
            return endpoints
        keep = [
            e for e in endpoints
            if hits.get(e.address, 0) == best
            and e.metric(StdMetric.QUEUED_REQUESTS) < self.queue_gate
        ]
        if not keep:
            return endpoints
        preds = req.state.get("predicted_latency") or {}
        if preds:  # TTFT load gate: saturated warm pod must not hoard its prefix
            warm_best = min(
                (preds[e.address][0] for e in keep if e.address in preds), default=None
            )
            overall_best = min(
                (preds[e.address][0] for e in endpoints if e.address in preds),
                default=None,
            )
            if warm_best is not None and overall_best is not None \
                    and warm_best - overall_best > self.ttft_penalty_ms:
                return endpoints
        return keep


@register_plugin("max-score-picker")
class MaxScorePicker:
    def pick(self, req: InferenceRequest, scored: dict[Endpoint, float]) -> Optional[Endpoint]:
        if not scored:
            return None
        mx = max(scored.values())
        best = [e for e, s in scored.items() if s >= mx - 1e-9]
        return random.choice(best)  # tie-break uniformly


@register_plugin("random-picker")
class RandomPicker:
    def pick(self, req: InferenceRequest, scored: dict[Endpoint, float]) -> Optional[Endpoint]:
        return random.choice(list(scored)) if scored else None


@register_plugin("weighted-random-picker")
class WeightedRandomPicker:
    def pick(self, req: InferenceRequest, scored: dict[Endpoint, float]) -> Optional[Endpoint]:
        if not scored:
            return None
        eps = 1e-6
        eps_weights = [s + eps for s in scored.values()]
        return random.choices(list(scored), weights=eps_weights, k=1)[0]
