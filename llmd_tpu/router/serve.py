"""CLI: python -m llmd_tpu.router.serve --config cfg.yaml --endpoints a:8000,b:8000

No-Kubernetes standalone mode (reference guides/no-kubernetes-deployment/): static
endpoint discovery via --endpoints or --endpoints-file; config is the plugin graph.
"""

from __future__ import annotations

import argparse
import asyncio

DEFAULT_CONFIG = """
plugins:
  - name: prefix-producer
    type: approx-prefix-cache-producer
    params: {blockSize: 16}
  - name: inflight
    type: inflight-load-producer
  - name: prefix
    type: prefix-cache-scorer
  - name: queue
    type: queue-depth-scorer
  - name: kv-util
    type: kv-cache-utilization-scorer
  - name: no-hit-lru-scorer
    type: no-hit-lru-scorer
schedulingProfiles:
  - name: default
    plugins:
      - {pluginRef: prefix, weight: 3}
      - {pluginRef: queue, weight: 2}
      - {pluginRef: kv-util, weight: 2}
      - {pluginRef: no-hit-lru-scorer, weight: 2}
"""


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None, help="FrameworkConfig YAML path")
    ap.add_argument("--endpoints", default=None, help="comma-separated addr list")
    ap.add_argument("--endpoints-file", default=None, help="file-discovery path")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--poll-interval", type=float, default=0.5)
    ap.add_argument("--extproc-port", type=int, default=None,
                    help="gateway mode: serve the Envoy ext_proc EPP gRPC here "
                         "(the HTTP port keeps serving /metrics and /health)")
    ap.add_argument("--extproc-failure-mode", default=None,
                    choices=["FailClose", "FailOpen"],
                    help="override the InferencePool failureMode for the "
                         "ext_proc EPP (no-kubernetes deployments have no "
                         "pool manifest to read it from)")
    ap.add_argument("--vllmgrpc-port", type=int, default=None,
                    help="serve the vLLM gRPC API (Generate/Embed) here — the "
                         "vllmgrpc-parser front, scheduled like HTTP traffic")
    ap.add_argument("--manifests", default=None,
                    help="InferencePool/InferenceObjective/InferenceModelRewrite/"
                         "VariantAutoscaling YAML (multi-doc)")
    ap.add_argument("--k8s-discovery", action="store_true",
                    help="discover endpoints by watching pods matching the "
                         "manifest InferencePool's selector/targetPorts")
    ap.add_argument("--ha-lease-file", default=None,
                    help="active-passive leader election via a local flock "
                         "lease (co-located replicas)")
    ap.add_argument("--ha-k8s-lease", default=None,
                    help="active-passive leader election via a "
                         "coordination.k8s.io Lease of this name")
    args = ap.parse_args()
    if args.ha_lease_file and args.ha_k8s_lease:
        raise SystemExit("--ha-lease-file and --ha-k8s-lease are exclusive")

    from llmd_tpu.core.config import FrameworkConfig
    from llmd_tpu.core.endpoint import EndpointPool
    from llmd_tpu.kv import plugins as _kv  # noqa: F401 (load registry)
    from llmd_tpu.router import plugins as _p  # noqa: F401
    from llmd_tpu.router import filters_pickers as _fp  # noqa: F401
    from llmd_tpu.router import scorers as _s  # noqa: F401
    from llmd_tpu.router.datalayer import add_static_endpoints
    from llmd_tpu.router.plugins import known_plugin_types
    from llmd_tpu.router.server import RouterServer

    if args.config:
        with open(args.config) as f:
            text = f.read()
    else:
        text = DEFAULT_CONFIG
    config = FrameworkConfig.from_yaml(text, known_types=known_plugin_types())

    manifests = None
    if args.manifests:
        from llmd_tpu.core.crds import load_manifest_yaml

        with open(args.manifests) as f:
            manifests = load_manifest_yaml(f.read())

    pool = EndpointPool()
    sources = []
    if args.endpoints_file:
        from llmd_tpu.router.discovery import FileSource

        sources.append(FileSource(pool, args.endpoints_file))
    if args.endpoints:
        add_static_endpoints(pool, args.endpoints.split(","))
    if args.k8s_discovery:
        if not manifests or not manifests.pools:
            raise SystemExit("--k8s-discovery needs --manifests with an InferencePool")
        from llmd_tpu.router.discovery import K8sWatchSource

        # every InferencePool in the manifest gets its own watch (e.g. separate
        # prefill/decode pools); all feed the one EndpointPool
        for p in manifests.pools:
            sources.append(K8sWatchSource(pool, p.selector, p.target_ports,
                                          namespace=p.namespace))

    server = RouterServer(
        config, pool, host=args.host, port=args.port,
        poll_interval_s=args.poll_interval,
        objectives=manifests.objectives_map() if manifests else None,
        model_rewrites=manifests.rewrites_map() if manifests else None,
    )

    elector = None
    if args.ha_lease_file or args.ha_k8s_lease:
        from llmd_tpu.router.ha import FileLease, K8sLease, LeaderElector, attach_ha

        lease = (FileLease(args.ha_lease_file) if args.ha_lease_file
                 else K8sLease(args.ha_k8s_lease))
        elector = LeaderElector(lease)
        attach_ha(server, elector)  # before start(): handlers bind at start

    async def run() -> None:
        await server.start()
        if elector is not None:
            await elector.start()
        for src in sources:
            await src.start()
        discovery = (f"{len(pool)} endpoints"
                     if not args.k8s_discovery
                     else f"{len(pool)} endpoints at startup; k8s watch active "
                          f"({len(sources)} pool(s))")
        msg = f"llmd-tpu router on http://{server.address} ({discovery})"
        if args.extproc_port is not None:
            from llmd_tpu.router.extproc import ExtProcEPP

            modes = {p.failure_mode for p in manifests.pools} if manifests and manifests.pools else set()
            if len(modes) > 1:
                print(f"warning: mixed failureModes {sorted(modes)}; "
                      "FailOpen wins for the shared EPP", flush=True)
            failure_mode = args.extproc_failure_mode or (
                "FailOpen" if "FailOpen" in modes else "FailClose")
            epp = ExtProcEPP(server, host=args.host, port=args.extproc_port,
                             failure_mode=failure_mode)
            await epp.start()
            msg += f"; ext-proc EPP on grpc://{epp.address} ({failure_mode})"
        if args.vllmgrpc_port is not None:
            from llmd_tpu.router.vllmgrpc import VllmGrpcFront

            vfront = VllmGrpcFront(server, host=args.host, port=args.vllmgrpc_port)
            await vfront.start()
            msg += f"; vllm-grpc on grpc://{vfront.address}"
        if elector is not None:
            msg += f"; HA role={'leader' if elector.is_leader else 'standby'}"
        print(msg, flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
