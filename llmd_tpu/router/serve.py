"""CLI: python -m llmd_tpu.router.serve --config cfg.yaml --endpoints a:8000,b:8000

No-Kubernetes standalone mode (reference guides/no-kubernetes-deployment/): static
endpoint discovery via --endpoints or --endpoints-file; config is the plugin graph.
"""

from __future__ import annotations

import argparse
import asyncio

DEFAULT_CONFIG = """
plugins:
  - name: prefix-producer
    type: approx-prefix-cache-producer
    params: {blockSize: 16}
  - name: inflight
    type: inflight-load-producer
  - name: prefix
    type: prefix-cache-scorer
  - name: queue
    type: queue-depth-scorer
  - name: kv-util
    type: kv-cache-utilization-scorer
  - name: no-hit-lru-scorer
    type: no-hit-lru-scorer
schedulingProfiles:
  - name: default
    plugins:
      - {pluginRef: prefix, weight: 3}
      - {pluginRef: queue, weight: 2}
      - {pluginRef: kv-util, weight: 2}
      - {pluginRef: no-hit-lru-scorer, weight: 2}
"""


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None, help="FrameworkConfig YAML path")
    ap.add_argument("--endpoints", default=None, help="comma-separated addr list")
    ap.add_argument("--endpoints-file", default=None, help="file-discovery path")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--poll-interval", type=float, default=0.5)
    args = ap.parse_args()

    from llmd_tpu.core.config import FrameworkConfig
    from llmd_tpu.core.endpoint import EndpointPool
    from llmd_tpu.kv import plugins as _kv  # noqa: F401 (load registry)
    from llmd_tpu.router import plugins as _p  # noqa: F401
    from llmd_tpu.router import filters_pickers as _fp  # noqa: F401
    from llmd_tpu.router import scorers as _s  # noqa: F401
    from llmd_tpu.router.datalayer import add_static_endpoints, load_endpoints_file
    from llmd_tpu.router.plugins import known_plugin_types
    from llmd_tpu.router.server import RouterServer

    if args.config:
        with open(args.config) as f:
            text = f.read()
    else:
        text = DEFAULT_CONFIG
    config = FrameworkConfig.from_yaml(text, known_types=known_plugin_types())

    pool = EndpointPool()
    if args.endpoints_file:
        load_endpoints_file(pool, args.endpoints_file)
    if args.endpoints:
        add_static_endpoints(pool, args.endpoints.split(","))

    server = RouterServer(config, pool, host=args.host, port=args.port,
                          poll_interval_s=args.poll_interval)

    async def run() -> None:
        await server.start()
        print(f"llmd-tpu router on http://{server.address} "
              f"({len(pool)} endpoints)", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
