"""Router server: standalone-mode proxy + EPP in one process.

The reference splits this across Envoy (ext-proc client) and the EPP gRPC server
(proxy.md:16-25, epp/README.md:13-16); standalone mode runs them co-located — this
server plays that combined role: parse → flow-control gate → schedule → forward to the
chosen endpoint → stream the response back, emitting x-llm-d-* headers and Prometheus
metrics (llm_d_epp_* family, observability/metrics.md:95-130).

P/D: when the disagg handler returns a prefill endpoint, the request is forwarded to
the DECODE endpoint with the x-prefiller-host-port header — the routing sidecar in
front of the decode engine orchestrates the P→D flow (disaggregation/README.md:104-131).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from dataclasses import dataclass
from typing import Any, Optional

import aiohttp
from aiohttp import web

from llmd_tpu.core.config import FrameworkConfig
from llmd_tpu.core.endpoint import EndpointPool
from llmd_tpu.core.request import (
    HDR_PREFILLER_HOST_PORT,
    HDR_REQUEST_TIMEOUT,
    HDR_TENANT,
    InferenceRequest,
    RequestOutcome,
    SamplingParams,
    clamp_request_id,
)
from llmd_tpu.router.datalayer import MetricsPoller
from llmd_tpu.router.flowcontrol import FlowController
from llmd_tpu.router.resilience import (
    RETRYABLE_STATUSES,
    ResilienceConfig,
    ResilienceManager,
)
from llmd_tpu.router.scheduler import Scheduler
from llmd_tpu.router.scorers import STATE_PREDICTED, STATE_TOKEN_IDS

GEN_PATHS = ("/v1/completions", "/v1/chat/completions", "/v1/embeddings",
             "/v1/responses")


@dataclass
class Rejection:
    """A non-dispatch admission outcome (admit_and_schedule error channel)."""

    status: int
    message: str
    # True = an enforced decision (shedding, standby gate) that FailOpen
    # gateways must still honour; False = the EPP couldn't answer.
    deliberate: bool = False


def parse_openai_request(path: str, body: dict, headers: dict[str, str]) -> InferenceRequest:
    """openai-parser (request-handling.md:50-73): /completions, /chat/completions,
    /embeddings, /responses, /conversations."""
    req = InferenceRequest.from_headers(headers)
    req.model = str(body.get("model", ""))
    if path.endswith("/v1/responses"):
        # Responses API: input is str | [{role, content}] (epp-http-apis.md:153)
        inp = body.get("input", "")
        if isinstance(inp, list):
            req.messages = [
                {"role": it.get("role", "user"), "content": it.get("content", "")}
                for it in inp if isinstance(it, dict)
            ]
            from llmd_tpu.core.request import mm_hashes_from_messages

            req.mm_hashes = mm_hashes_from_messages(req.messages)
        else:
            req.prompt = str(inp)
    elif "messages" in body:
        req.messages = body["messages"]
        from llmd_tpu.core.request import mm_hashes_from_messages

        req.mm_hashes = mm_hashes_from_messages(body["messages"])
    elif "input" in body:  # /v1/embeddings: input is str | [str] | [int] | [[int]]
        inp = body["input"]
        req.prompt = inp if isinstance(inp, str) else json.dumps(inp)
    else:
        req.prompt = str(body.get("prompt", ""))
    req.lora_adapter = body.get("lora_adapter")
    # Structured outputs (llmd_tpu/structured): malformed specs fail here as
    # ValueError -> 400, BEFORE the request ever reaches flow control; valid
    # specs ride through in sampling so scorers/predictors can see them.
    from llmd_tpu.structured import validate_structured_body

    validate_structured_body(body)
    req.sampling = SamplingParams(
        max_tokens=int(body.get("max_output_tokens", body.get("max_tokens", 16))),
        temperature=float(body.get("temperature", 1.0)),
        guided_choice=body.get("guided_choice"),
        guided_regex=body.get("guided_regex"),
        response_format=body.get("response_format"),
        logit_bias=body.get("logit_bias"),
    )
    req.streaming = bool(body.get("stream", False))
    req.byte_size = len(json.dumps(body))
    return req


def parse_passthrough_request(path: str, body: dict, headers: dict[str, str]) -> InferenceRequest:
    """passthrough-parser (request-handling.md:75): model-agnostic — content is
    NOT interpreted, so payload-driven plugins (prefix scorers, token producer)
    see an empty prompt and score nothing; routing runs on pool state alone.
    Model/objective still come from headers so objective priorities apply."""
    req = InferenceRequest.from_headers(headers)
    lower = {k.lower(): v for k, v in headers.items()}
    req.model = lower.get("x-model", "")
    try:
        req.byte_size = len(json.dumps(body))
    except (TypeError, ValueError):
        req.byte_size = 0
    return req


PARSERS = {
    "openai-parser": parse_openai_request,
    "passthrough-parser": parse_passthrough_request,
}


class RouterServer:
    def __init__(
        self,
        config: FrameworkConfig,
        pool: EndpointPool,
        host: str = "127.0.0.1",
        port: int = 8080,
        poll_interval_s: float = 0.5,
        objectives: Optional[dict[str, int]] = None,  # objective name → priority
        model_rewrites: Optional[dict[str, list[tuple[str, float]]]] = None,
    ) -> None:
        self.config = config
        self.pool = pool
        self.host, self.port = host, port
        self.ctx: dict[str, Any] = {}
        kv_cfg = (config.raw.get("kvEvents") or {}) if config.raw else {}
        if kv_cfg.get("indexBackend") or kv_cfg.get("indexParams"):
            # seed the index BEFORE plugin construction: the precise-prefix
            # producer setdefaults CTX_KV_INDEX at plugin-build time, so a
            # kvEvents-configured backend created later would be constructed
            # and silently discarded (each replica running a private
            # in-memory index instead of the configured shared one)
            from llmd_tpu.kv.index_backends import build_index
            from llmd_tpu.kv.plugins import CTX_KV_INDEX

            self.ctx[CTX_KV_INDEX] = build_index(
                kv_cfg.get("indexBackend", "in-memory"),
                **(kv_cfg.get("indexParams") or {}))
        self.scheduler = Scheduler(config, pool, self.ctx)
        # Global KV plane (llmd_tpu/kvplane, docs/kv-plane.md): LLMD_KV_PLANE
        # swaps prefix producers/scorers on the built scheduler and enables
        # cross-engine pull stamping. "off" (the default) is a strict no-op —
        # the config graph behaves bitwise-identically to a plane-less build.
        from llmd_tpu.kvplane import KVPlane

        self.kvplane = KVPlane.from_env(self.ctx, pool)
        self.kvplane.install(self.scheduler)
        self.flow: Optional[FlowController] = (
            FlowController(config.flow_control, pool, self.ctx)
            if config.flow_control.enabled else None
        )
        self.poller = MetricsPoller(pool, interval_s=poll_interval_s)
        # Producers exposing an async pre-schedule step (token-producer render call).
        self._async_producers = [
            p for p in self.scheduler.producers if hasattr(p, "aproduce")
        ]
        # KV-event subscription (precise prefix routing): on when the config declares
        # a precise producer or an explicit kvEvents section (kv-indexer.md:67-87).
        self.kv_subscriber = None
        wants_precise = any(p.type == "precise-prefix-cache-producer" for p in config.plugins)
        if wants_precise or self.kvplane.active or (config.raw and "kvEvents" in config.raw):
            from llmd_tpu.kv.index_backends import build_index
            from llmd_tpu.kv.plugins import CTX_KV_INDEX
            from llmd_tpu.kv.subscriber import KVEventSubscriberManager

            index = self.ctx.setdefault(CTX_KV_INDEX, build_index(
                kv_cfg.get("indexBackend", "in-memory"),
                **(kv_cfg.get("indexParams") or {})))
            self.kv_subscriber = KVEventSubscriberManager(
                index, pool,
                topic_filter=kv_cfg.get("topicFilter", "kv@"),
                default_events_port=kv_cfg.get("port"),
                bind_port=kv_cfg.get("bindPort"),
            )
        self.kvplane.subscriber = self.kv_subscriber  # feed-staleness signal
        self.objectives = objectives or {}
        self.model_rewrites = model_rewrites or {}
        # Request parser (request-handling.md:73-75): openai-parser default;
        # passthrough-parser routes without payload interpretation.
        parser_name = (config.raw.get("parser") if config.raw else None) or "openai-parser"
        if parser_name not in PARSERS:
            raise ValueError(f"unknown parser {parser_name!r}; known: {sorted(PARSERS)}")
        self._parser = PARSERS[parser_name]
        # Scheduling runs off the event loop on ONE worker thread: plugins may block
        # (sidecar predictor RPC) and share per-request mutable state — a single
        # thread keeps them serialized while the proxy loop stays responsive.
        import concurrent.futures

        self._sched_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="epp-sched"
        )
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        # EPP metric families (llm_d_epp_* / igw_*) live in one shared
        # registry; /metrics renders via Registry.expose() — the same code
        # path the engine server uses. Legacy counter dicts (scheduler.metrics,
        # flow.metrics) surface through scrape-time callbacks, so their owners
        # keep single-writer semantics.
        from llmd_tpu.obs.metrics import Registry, register_router_metrics

        self.registry = Registry()
        self.metrics = register_router_metrics(self.registry)
        sched = self.scheduler.metrics
        self.metrics.scheduled.set_function(lambda: sched["scheduled_total"])
        self.metrics.rejected.set_function(lambda: sched["rejected_total"])
        self.metrics.pd_splits.set_function(lambda: sched["pd_splits_total"])
        self.metrics.pd_aggregated.set_function(
            lambda: sched["pd_aggregated_total"])
        for fam, key in ((self.metrics.flow_enqueued, "enqueued_total"),
                         (self.metrics.flow_dispatched, "dispatched_total"),
                         (self.metrics.flow_rejected_capacity,
                          "rejected_capacity_total"),
                         (self.metrics.flow_evicted_ttl, "evicted_ttl_total"),
                         (self.metrics.flow_queue_depth, "queue_depth")):
            fam.set_function(
                lambda k=key: self.flow.metrics[k] if self.flow else 0)
        self.metrics.igw_queue_depth.set_function(
            lambda: self.flow.metrics["queue_depth"] if self.flow else 0)
        self.metrics.igw_running.set_function(
            lambda: sum(self.ctx.get("inflight_requests", {}).values()))
        if self.flow is not None:
            self.flow.queue_wait_histogram = self.metrics.flow_queue_wait
        # OTel-shaped tracing (docs/operations/observability/tracing.md):
        # proxy/EPP span with child hops propagated via traceparent
        from llmd_tpu.obs.tracing import global_tracer

        self.tracer = global_tracer()
        # always-on per-request flight recorder (obs/events.py): the router
        # plane records arrival → flow control → routing decision → forward →
        # response; /debug/requests exposes it live
        from llmd_tpu.obs.events import FlightRecorder

        self.flight = FlightRecorder.from_env(tracer=self.tracer)
        if self.flow is not None:
            self.flow.flight = self.flight
            self.metrics.flow_evicted_deadline.set_function(
                lambda: self.flow.metrics["evicted_deadline_total"])
        # Resilience layer (router/resilience.py): deadlines, retries, per-
        # endpoint circuit breakers, drain awareness, hedging. The breaker
        # filter hooks into every scheduler pick; the poller's scrape failures
        # feed it as a passive-health signal.
        self.resilience = ResilienceManager(
            ResilienceConfig.from_env(), metrics=self.metrics,
            flight=self.flight)
        self.scheduler.endpoint_filter = self.resilience.filter_endpoints
        self.poller.on_scrape_error = self.resilience.note_scrape_error
        self.metrics.scrape_errors.set_function(
            lambda: self.poller.scrape_error_count)
        self.metrics.breaker_open_endpoints.set_function(
            lambda: len(self.resilience.open_endpoints()))
        plane = self.kvplane
        self.metrics.kvplane_precise.set_function(
            lambda: plane.stats["precise_requests"])
        self.metrics.kvplane_degraded.set_function(
            lambda: plane.stats["degraded_requests"])
        self.metrics.kvplane_lookups.set_function(
            lambda: plane.stats["lookups"])
        self.metrics.kvplane_lookup_hits.set_function(
            lambda: plane.stats["lookup_hits"])
        self.metrics.kvplane_pulls_stamped.set_function(
            lambda: plane.stats["pulls_planned"])
        self.metrics.kvplane_durable_pulls_stamped.set_function(
            lambda: plane.stats.get("durable_pulls_planned", 0))
        self.metrics.kvplane_index_blocks.set_function(
            lambda: len(plane.index) if plane.index is not None else 0)
        self.metrics.kvplane_feed_age.set_function(plane.feed_age_s)
        # SLO objectives + burn rate (obs/slo.py, LLMD_SLO_*): per-tenant
        # attainment/burn gauges are scrape-time callbacks over the rolling
        # windows; individual breaches land on the flight timeline.
        from llmd_tpu.obs.slo import SLOEngine

        self.slo = SLOEngine.from_env()
        self.slo.breach_counter = self.metrics.slo_breaches
        self.metrics.slo_attainment.set_labels_function(
            lambda: self.slo.gauge_samples("attainment"))
        self.metrics.slo_burn_rate.set_labels_function(
            lambda: self.slo.gauge_samples("burn"))
        # Latency attribution: fold each retired router timeline into the
        # phase ledger and export llmd_tpu:request_phase_seconds.
        from llmd_tpu.obs.attribution import attach_phase_exporter

        attach_phase_exporter(self.flight, self.metrics.request_phase)
        # Decision plane (obs/decisions.py): chained AFTER the phase
        # exporter (on_finish is a single slot — the decision hook wraps
        # and forwards). When the ledger is off nothing is attached and
        # the scheduler records no detail: the off path costs nothing.
        from llmd_tpu.obs.decisions import attach_decision_exporter

        if self.scheduler.record_decisions:
            attach_decision_exporter(self.flight, self.metrics,
                                     plane="router")
        # Fleet rollup plane (obs/fleet.py): rides the poller's extractor
        # chain; one router scrape then answers fleet tok/s, HBM headroom,
        # KV residency, fabric/stall counts without touching any replica.
        from llmd_tpu.obs.fleet import FleetRollup

        self.fleet = FleetRollup()
        self.poller.extractors.append(self.fleet)
        self.fleet.bind_gauges(self.metrics)
        # Discovery eviction: an endpoint leaving the pool (scale-down,
        # replica death) takes its breaker/draining/error-count state with
        # it — churned replicas must not leak state across scale cycles.
        # The KV index evicts on the SAME listener: without this, a router
        # whose subscriber isn't running against the departed pod (centralized
        # mode, or no subscriber at all) keeps its blocks forever and the
        # index grows unboundedly across controller churn.
        def _on_pool_event(kind: str, ep) -> None:
            if kind == "removed":
                self.resilience.forget(ep.address)
                self.poller.forget(ep.address)
                idx = self.kvplane.index
                if idx is not None:
                    idx.remove_pod(ep.address)

        self._pool_listener = _on_pool_event
        pool.subscribe(self._pool_listener)
        # extra Prometheus providers (ext-proc EPP front, HA coordinator, ...):
        # callables returning lines, appended to /metrics
        self.extra_metrics: list[Any] = []

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        await self.poller.start()
        if self.flow:
            await self.flow.start()
        if self.kv_subscriber:
            await self.kv_subscriber.start()
        app = web.Application(client_max_size=64 * 1024 * 1024)
        for path in GEN_PATHS:
            app.router.add_post(path, self._handle_generate)
        # Conversations API: pod-local state, so traffic is sticky by id —
        # hash(cid) picks the pod deterministically on every EPP replica
        app.router.add_post("/v1/conversations", self._handle_conversation)
        app.router.add_route("*", "/v1/conversations/{tail:.*}",
                             self._handle_conversation)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        app.router.add_get("/v1/models", self._models)
        # runtime canary control: the rollout driver (tools/rollout.py) shifts
        # InferenceModelRewrite weights through here stage by stage
        app.router.add_get("/admin/model-rewrites", self._get_rewrites)
        app.router.add_post("/admin/model-rewrites", self._set_rewrites)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/requests/{rid}", self._debug_request)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        self.pool.unsubscribe(self._pool_listener)
        await self.poller.stop()
        if self.flow:
            await self.flow.stop()
        if self.kv_subscriber:
            await self.kv_subscriber.stop()
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()
        self._sched_executor.shutdown(wait=False)

    # ------------------------------------------------------------------
    async def _get_rewrites(self, request: web.Request):
        return web.json_response({
            m: [[t, w] for t, w in targets]
            for m, targets in self.model_rewrites.items()
        })

    async def _set_rewrites(self, request: web.Request):
        """Merge-update rewrite entries: {"model": [["target", weight], ...]}.
        An empty target list deletes the entry (traffic reverts to the plain
        model name). The rollout driver shifts canary weights through this."""
        import math

        try:
            body = await request.json()
            updates = {
                m: [(str(t), float(w)) for t, w in targets]
                for m, targets in body.items()
            }
        except Exception:
            return web.json_response(
                {"error": "body must be {model: [[target, weight], ...]}"},
                status=400)
        for m, targets in updates.items():
            # NaN/inf pass both the <0 and <=0 checks and then poison
            # random.choices' cumulative weights (every comparison False →
            # deterministic first pick): finite-and-nonnegative only
            if any(not math.isfinite(w) or w < 0 for _, w in targets):
                return web.json_response(
                    {"error": f"rewrite {m}: weights must be finite and >= 0"},
                    status=400)
            if targets and sum(w for _, w in targets) <= 0:
                return web.json_response(
                    {"error": f"rewrite {m}: zero total weight"}, status=400)
        for m, targets in updates.items():
            if targets:
                self.model_rewrites[m] = targets
            else:
                self.model_rewrites.pop(m, None)
        return web.json_response({"status": "ok",
                                  "rewrites": len(self.model_rewrites)})

    def _rewrite_model(self, req: InferenceRequest, body: dict) -> None:
        """InferenceModelRewrite: weighted model-name rewrite for canary/A-B
        (docs/api-reference/inferencemodelrewrite.md)."""
        import random

        targets = self.model_rewrites.get(req.model)
        if not targets:
            return
        names = [t[0] for t in targets]
        weights = [t[1] for t in targets]
        chosen = random.choices(names, weights=weights, k=1)[0]
        body["model"] = chosen
        req.state["model_rewritten_to"] = chosen

    @staticmethod
    def _profile_scores(result) -> Optional[dict]:
        """Flatten SchedulingResult per-profile endpoint scores for the flight
        timeline (the "why" behind a routing decision)."""
        out = {}
        for name, run in (result.profiles or {}).items():
            scores = getattr(run, "scores", None)
            if scores:
                out[name] = {ep.address: round(s, 4)
                             for ep, s in scores.items()}
        return out or None

    def _decision_payload(self, req: InferenceRequest, result) -> Optional[dict]:
        """Flatten a SchedulingResult's decision detail into the
        ``route_decision`` event payload (obs/decisions.py): per-profile
        filter eliminations, top-k ranked candidates, weighted per-scorer
        breakdown for the chosen endpoint and runner-up, tie width, regret,
        plus the predictor's stamps for the calibration join at retire."""
        from llmd_tpu.obs.decisions import regret_topk
        from llmd_tpu.router.latency_plugins import predicted_e2e_ms

        topk = regret_topk()
        profs: dict = {}
        primary_regret = None
        for name, run in (result.profiles or {}).items():
            det = getattr(run, "detail", None)
            if det is None:
                continue
            scores = run.scores or {}
            ranked = sorted(scores.items(),
                            key=lambda kv: (-kv[1], kv[0].address))
            entry: dict = {
                "candidates": det["candidates"],
                "tie": det["tie"],
                "top": [[ep.address, round(s, 4)] for ep, s in ranked[:topk]],
            }
            if det["filters"]:
                entry["filters"] = det["filters"]
            chosen = run.endpoint.address if run.endpoint is not None else None
            if chosen is not None:
                entry["chosen"] = chosen
                runner = next((ep.address for ep, _ in ranked
                               if ep.address != chosen), None)
                breakdown: dict = {}
                for sname, weight, smap in det["scorers"]:
                    for ep, s in smap.items():
                        if ep.address in (chosen, runner):
                            breakdown.setdefault(ep.address, {})[sname] = \
                                round(weight * s, 4)
                if breakdown:
                    entry["breakdown"] = breakdown
                if runner is not None:
                    chosen_score = next(
                        (s for ep, s in ranked if ep.address == chosen), 0.0)
                    best_alt = max(
                        (s for ep, s in scores.items()
                         if ep.address != chosen), default=None)
                    if best_alt is not None:
                        entry["regret"] = round(chosen_score - best_alt, 4)
                        if (result.endpoint is not None
                                and chosen == result.endpoint.address):
                            primary_regret = entry["regret"]
            profs[name] = entry
        if not profs:
            return None
        payload: dict = {"profiles": profs}
        if primary_regret is not None:
            payload["regret"] = primary_regret
        if result.pre_drops:
            payload.update(result.pre_drops)
            if result.pre_drops.get("resilience_dropped"):
                breakers = self.resilience.attempt_states(
                    e.address for e in self.pool.list())
                if breakers:
                    payload["breakers"] = breakers
        from llmd_tpu.kvplane import STATE_KV_PLANE

        kv_path = req.state.get(STATE_KV_PLANE)
        if kv_path:
            payload["kv_plane"] = kv_path  # "precise" | degraded-path reason
        if result.endpoint is not None:
            pred = (req.state.get(STATE_PREDICTED) or {}).get(
                result.endpoint.address)
            if pred is not None:
                payload["predicted_ttft_ms"] = round(float(pred[0]), 3)
                payload["predicted_e2e_ms"] = round(
                    predicted_e2e_ms(req, pred), 3)
        pd = getattr(result, "pd", None)
        if pd:
            payload["pd"] = pd  # disagg decider outcome + predicted deltas
        return payload

    def _record_route_decision(self, req: InferenceRequest, result,
                               attempt: Optional[int] = None) -> None:
        """Emit the decision ledger's ``route_decision`` event. Gated on the
        scheduler's cached knob so the off path never builds the payload."""
        if not self.scheduler.record_decisions:
            return
        payload = self._decision_payload(req, result)
        if payload is None:
            return
        if attempt is not None:
            payload["attempt"] = attempt
        self.flight.record(req.request_id, "route_decision", **payload)

    def _observe_e2e(self, seconds: float, exemplar=None) -> None:
        # promql.md alert HighP99Latency reads these buckets; the exemplar
        # (trace_id of the active span) lets Grafana jump bucket → trace
        self.metrics.e2e.observe(seconds, exemplar=exemplar)

    def _observe_slo(self, req: InferenceRequest, objective: str,
                     seconds: float) -> None:
        """Feed one latency sample into the SLO engine; a breach lands on
        the request's flight timeline (and the breach counter via the
        engine's hook) so slow-tail triage starts from the ledger."""
        if not self.slo.enabled:
            return
        if self.slo.observe(req.tenant, objective, seconds):
            self.flight.record(req.request_id, "slo_breach",
                               objective=objective, tenant=req.tenant,
                               latency_ms=round(seconds * 1e3, 3))

    def _account_usage(self, req: InferenceRequest, usage: dict) -> None:
        """Per-tenant token accounting from upstream usage payloads."""
        for key, fam in (("prompt_tokens", self.metrics.tenant_prompt_tokens),
                         ("completion_tokens",
                          self.metrics.tenant_completion_tokens)):
            try:
                n = float(usage.get(key) or 0)
            except (TypeError, ValueError):
                continue
            if n > 0:
                fam.labels(tenant=req.tenant, model=req.model).inc(n)

    def prepare_request(self, path: str, body: dict,
                        headers: dict[str, str]) -> InferenceRequest:
        """Parse + apply objectives and model rewrite (mutates ``body`` on
        rewrite). Shared preamble of the standalone HTTP path and the
        gateway-mode ext-proc path."""
        req = self._parser(path, body, headers)
        lower = {k.lower(): v for k, v in headers.items()}
        # clamped, not trusted: client ids become flight-recorder keys and
        # exemplar labels, so hostile bytes fall back to a generated id
        req.request_id = clamp_request_id(lower.get("x-request-id"))
        self.metrics.tenant_requests.labels(tenant=req.tenant,
                                            model=req.model).inc()
        if req.objective and req.objective in self.objectives:
            req.priority = self.objectives[req.objective]
        if req.timeout_s is None:
            # no client deadline header: the router default still bounds every
            # attempt (replacing the old hard-coded 600s forward timeout)
            req.timeout_s = self.resilience.cfg.request_timeout_s
        self._rewrite_model(req, body)
        return req

    async def _flow_gate(self, req: InferenceRequest, span=None) -> Optional[Rejection]:
        """Flow-control admission shared by the scheduled AND sticky paths."""
        if self.flow:
            if span:
                span.add_event("flow_control.enqueue")
            outcome = await self.flow.enqueue_and_wait(req)
            if outcome is not RequestOutcome.DISPATCHED:
                self.metrics.errors.inc()
                return Rejection(outcome.http_status,
                                 f"flow control: {outcome.value}", deliberate=True)
        return None

    async def admit_and_schedule(self, req: InferenceRequest, span=None):
        """Flow-control gate → async producers → scheduler pick.

        Returns (result, None) on success or (None, Rejection) — one admission
        semantics for both serving fronts. ``Rejection.deliberate`` marks
        enforced admission decisions (load shedding, standby gating) that a
        FailOpen gateway must NOT bypass, vs EPP-can't-answer conditions
        (no endpoint) that failureMode may pass through."""
        rej = await self._flow_gate(req, span)
        if rej is not None:
            return None, rej
        for p in self._async_producers:
            await p.aproduce(req, self.pool.list(), self._session)
        if span:
            span.add_event("schedule.start")
        result = await self._schedule(req)
        if result.endpoint is None:
            self.metrics.errors.inc()
            return None, Rejection(503, f"no endpoint: {result.rejected}")
        rem = req.remaining_s()
        if rem is not None and rem <= 0:
            # flow wait + scheduling ate the whole client budget: a 504 now is
            # honest; dispatching with a stale budget just wastes an endpoint
            self.metrics.deadline_exceeded.inc()
            self.flight.record(req.request_id, "deadline_exceeded",
                               where="post_schedule")
            return None, Rejection(504, "deadline exceeded before dispatch",
                                   deliberate=True)
        return result, None

    async def _schedule(self, req: InferenceRequest,
                        exclude: Optional[set] = None):
        """Scheduler pick on the single worker thread; ``exclude`` holds
        endpoints already tried this request (retry/hedge re-pick)."""
        return await asyncio.get_running_loop().run_in_executor(
            self._sched_executor, self.scheduler.schedule, req, exclude)

    def _note_outcome(self, address: str, status: int) -> None:
        """Feed a completed response into the breaker: any 5xx is a failure
        signal, everything else (including 4xx client errors) proves the
        endpoint's serving path works."""
        if status >= 500:
            self.resilience.on_failure(address, reason=f"http {status}")
        else:
            self.resilience.on_success(address)

    async def _post_maybe_hedged(self, req: InferenceRequest, target,
                                 path: str, body, fwd_headers: dict,
                                 timeout_s: float, first_attempt: bool):
        """POST to ``target``; on the first attempt of a hedge-eligible
        request, race a delayed second attempt on another endpoint ("The Tail
        at Scale" hedging). Returns ``(response, endpoint_that_answered)``;
        raises the transport error when every leg fails."""
        timeout = aiohttp.ClientTimeout(total=timeout_s)

        def post(ep):
            return self._session.post(f"http://{ep.address}{path}", json=body,
                                      headers=fwd_headers, timeout=timeout)

        if not first_attempt or not self.resilience.hedge_eligible(req):
            return await post(target), target
        primary = asyncio.ensure_future(post(target))
        delay = self.resilience.hedge_delay_s()
        done, _ = await asyncio.wait({primary}, timeout=delay)
        if primary in done:
            return primary.result(), target  # under the hedge delay: no hedge
        alt = await self._schedule(req, {target.address})
        if alt.endpoint is None:
            return await primary, target  # nowhere to hedge to
        self.metrics.hedges.inc()
        self.flight.record(req.request_id, "hedge", primary=target.address,
                           secondary=alt.endpoint.address,
                           delay_ms=round(delay * 1e3, 3))
        secondary = asyncio.ensure_future(post(alt.endpoint))
        legs = {primary: target, secondary: alt.endpoint}
        pending = set(legs)
        winner = None  # first leg answering with a non-5xx
        # a 5xx leg is kept as fallback: returned unconsumed if nothing wins
        # so the caller's retry loop can judge its (retryable) status
        fallback = None
        error = None
        while pending and winner is None:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                try:
                    r = t.result()
                except Exception as e:
                    error = e
                    continue
                if r.status < 500 and winner is None:
                    winner = t
                elif fallback is None:
                    fallback = t
                else:
                    r.release()
        chosen = winner if winner is not None else fallback
        for t, ep in legs.items():
            if t is chosen:
                continue
            if not t.done():
                t.cancel()
            asyncio.ensure_future(self._reap_leg(t))
            # the loser's pick also ran pre_request: settle its producer
            # bookkeeping here (the caller only settles the returned leg);
            # when both legs fail, the caller reports the primary itself
            if chosen is not None or t is secondary:
                self.scheduler.post_response(req, ep, {"hedge_loser": True})
        if chosen is None:
            raise error
        if chosen is secondary and winner is not None:
            self.metrics.hedge_wins.inc()
        return chosen.result(), legs[chosen]

    @staticmethod
    async def _reap_leg(task) -> None:
        """Release a cancelled/abandoned hedge leg's connection quietly."""
        try:
            r = await task
        except BaseException:
            return
        r.release()

    def _sticky_endpoint(self, conversation_id: str):
        """Conversation→pod mapping: rendezvous (highest-random-weight) hashing,
        identical on every replica AND stable under pool changes — adding or
        removing a pod only remaps the conversations that pod itself owned,
        never the rest (a modulo scheme would 404 nearly every live
        conversation on any scale event)."""
        import hashlib as _h

        from llmd_tpu.core.endpoint import EndpointRole

        # decode-capable pods only: Conversations/Responses state and the
        # decode path don't exist on a prefill-only pod, so pinning a
        # conversation there (which the scheduler's own filters would have
        # excluded) would 404 every follow-up turn
        eps = [e for e in self.pool.list() if e.role != EndpointRole.PREFILL]
        if not eps:
            return None
        cid = conversation_id.encode()
        return max(eps, key=lambda e: _h.sha256(cid + b"@" + e.address.encode()).digest())

    async def _forward_sticky(self, target, method: str, path: str, body,
                              timeout_s: float,
                              fwd_headers: Optional[dict] = None):
        """Proxy one request to its sticky pod, echoing the pick header and
        propagating trace/request-id headers."""
        try:
            resp = await self._session.request(
                method, f"http://{target.address}{path}",
                json=body, headers=fwd_headers,
                timeout=aiohttp.ClientTimeout(total=timeout_s))
            payload = await resp.read()
        except Exception as e:
            self.metrics.errors.inc()
            return web.json_response(
                {"error": {"message": f"upstream error: {e}"}}, status=502)
        return web.Response(body=payload, status=resp.status,
                            content_type=resp.content_type,
                            headers={"x-llm-d-endpoint": target.address})

    async def _handle_conversation(self, request: web.Request):
        """Forward Conversations API traffic to its sticky pod. Creation gets a
        router-assigned id so the hash mapping exists before any pod is asked."""
        self.metrics.requests.inc()
        body = None
        if request.method == "POST":
            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:
                return web.json_response({"error": {"message": "invalid JSON"}},
                                         status=400)
        tail = request.match_info.get("tail", "")
        cid = tail.split("/", 1)[0] if tail else None
        if cid is None:  # create
            body = dict(body or {})
            cid = body.setdefault("id", f"conv_{uuid.uuid4().hex[:12]}")
        target = self._sticky_endpoint(cid)
        if target is None:
            return web.json_response({"error": {"message": "no endpoints"}}, status=503)
        return await self._forward_sticky(target, request.method, request.path,
                                          body, timeout_s=60)

    def _stamp_kv_pull(self, req, target, body: dict) -> None:
        """KV plane: when a peer engine holds materially more of this prompt's
        prefix than the chosen target, stamp transfer params so the target
        PULLS the prefix over the KV wire instead of re-prefilling it.
        Re-invoked on every retry re-pick so the stamp tracks the target;
        client-supplied kv_transfer_params (P/D flows) are never touched."""
        if not self.kvplane.active:
            return
        stamped = bool(req.state.get("kv_plane_stamped"))
        if body.get("kv_transfer_params") is not None and not stamped:
            return  # client-owned transfer params — leave untouched
        if stamped:
            body.pop("kv_transfer_params", None)
            req.state["kv_plane_stamped"] = False
        plan = self.kvplane.plan_pull(req, target.address)
        if plan is None:
            return
        peer = plan.pop("peer", None)
        saved = plan.pop("saved_tokens_est", None)
        body["kv_transfer_params"] = plan
        req.state["kv_plane_stamped"] = True
        self.flight.record(req.request_id, "kv_pull_stamped",
                           endpoint=target.address, peer=peer,
                           blocks=len(plan.get("block_hashes") or ()),
                           saved_tokens_est=saved)

    async def _handle_generate(self, request: web.Request):
        t_start = time.monotonic()
        self.metrics.requests.inc()
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        headers = dict(request.headers)
        # /v1/responses continuing a conversation must land on the pod holding
        # that conversation's items (and its KV prefix). Admission (flow
        # control, objectives, tracing) still applies — sticky affinity only
        # replaces the scheduler PICK, it is not a shedding bypass.
        from llmd_tpu.obs.tracing import extract_traceparent

        if request.path.endswith("/v1/responses") and body.get("conversation"):
            try:
                req = self.prepare_request(request.path, body, headers)
            except ValueError as e:  # malformed structured spec → 400 pre-flow
                return web.json_response({"error": {"message": str(e)}},
                                         status=400)
            # span BEFORE the flow gate (parity with the scheduled path) so
            # the flight record carries a trace id from its first event on
            span = self.tracer.start_span(
                "epp.request", parent=extract_traceparent(headers),
                **{"llm_d.request_id": req.request_id, "llm_d.model": req.model,
                   "http.route": request.path, "llm_d.sticky": True})
            self.flight.start(req.request_id, model=req.model,
                              trace_id=span.context.trace_id,
                              tenant=req.tenant)
            self.flight.record(req.request_id, "arrival", path=request.path,
                               sticky=True)
            rej = await self._flow_gate(req, span)
            if rej is not None:
                self.flight.finish(req.request_id, event="rejected",
                                   status="rejected", reason=rej.message,
                                   http_status=rej.status)
                span.set_error(rej.message)
                span.end()
                return web.json_response({"error": {"message": rej.message}},
                                         status=rej.status)
            target = self._sticky_endpoint(str(body["conversation"]))
            if target is None:
                self.metrics.errors.inc()
                self.flight.finish(req.request_id, event="error",
                                   status="error", reason="no endpoints",
                                   http_status=503)
                span.set_error("no endpoints")
                span.end()
                return web.json_response({"error": {"message": "no endpoints"}},
                                         status=503)
            span.set_attribute("llm_d.endpoint", target.address)
            self.flight.record(req.request_id, "routing_decision",
                               endpoint=target.address, sticky=True)
            self.flight.record(req.request_id, "forward",
                               endpoint=target.address)
            rem = req.remaining_s()
            budget = (rem if rem is not None
                      else self.resilience.cfg.request_timeout_s)
            if budget <= 0:
                self.metrics.deadline_exceeded.inc()
                self.flight.record(req.request_id, "deadline_exceeded",
                                   where="sticky")
                self.flight.finish(req.request_id, event="rejected",
                                   status="rejected",
                                   reason="deadline exceeded", http_status=504)
                span.set_error("deadline exceeded")
                span.end()
                return web.json_response(
                    {"error": {"message": "deadline exceeded"}}, status=504)
            resp = await self._forward_sticky(
                target, "POST", request.path, body, timeout_s=budget,
                fwd_headers={"content-type": "application/json",
                             "traceparent": span.traceparent(),
                             "x-request-id": req.request_id,
                             HDR_TENANT: req.tenant,
                             HDR_REQUEST_TIMEOUT: f"{budget:.3f}"})
            # sticky traffic can't route around its pod, but its outcomes
            # still teach the breaker (protects the scheduled path)
            self._note_outcome(target.address, resp.status)
            if resp.status >= 500:
                self.flight.finish(req.request_id, event="error",
                                   status="error", http_status=resp.status)
            else:
                self.flight.finish(req.request_id, event="response",
                                   status="finished", http_status=resp.status)
            span.end()
            return resp
        try:
            req = self.prepare_request(request.path, body, headers)
        except ValueError as e:  # malformed structured spec → 400 pre-flow
            return web.json_response({"error": {"message": str(e)}}, status=400)

        span = self.tracer.start_span(
            "epp.request", parent=extract_traceparent(headers),
            **{"llm_d.request_id": req.request_id, "llm_d.model": req.model,
               "http.route": request.path})
        self.flight.start(req.request_id, model=req.model,
                          trace_id=span.context.trace_id, tenant=req.tenant)
        self.flight.record(req.request_id, "arrival", path=request.path)

        result, err = await self.admit_and_schedule(req, span=span)
        if err is not None:
            self.flight.finish(
                req.request_id,
                event="rejected" if err.deliberate else "error",
                status="rejected" if err.deliberate else "error",
                reason=err.message, http_status=err.status)
            span.set_error(err.message)
            span.end()
            return web.json_response({"error": {"message": err.message}},
                                     status=err.status)
        span.set_attribute("llm_d.endpoint", result.endpoint.address)
        span.add_event("proxy.forward")
        self.flight.record(
            req.request_id, "routing_decision",
            endpoint=result.endpoint.address,
            prefill_endpoint=(result.prefill_endpoint.address
                              if result.prefill_endpoint else None),
            latency_ms=round(result.latency_s * 1e3, 3),
            scores=self._profile_scores(result))
        self._record_route_decision(req, result)
        self.flight.record(req.request_id, "forward",
                           endpoint=result.endpoint.address)

        target = result.endpoint
        prefill = result.prefill_endpoint
        self._stamp_kv_pull(req, target, body)
        # Bounded retry loop: connect errors, attempt timeouts, and retryable
        # statuses (502/503/504) BEFORE any response body re-schedule on a
        # different endpoint (excluded set = llm-d excluded_runner_ids). Once
        # a non-retryable response arrives the request is committed to it.
        excluded = {target.address}
        attempt = 1
        resp = None
        while True:
            rem = req.remaining_s()
            if rem is not None and rem <= 0:
                self.metrics.deadline_exceeded.inc()
                self.flight.record(req.request_id, "deadline_exceeded",
                                   where="retry_loop", attempts=attempt - 1)
                self.flight.finish(req.request_id, event="rejected",
                                   status="rejected",
                                   reason="deadline exceeded",
                                   http_status=504)
                span.set_error("deadline exceeded")
                span.end()
                return web.json_response(
                    {"error": {"message": "deadline exceeded"}}, status=504)
            budget = rem if rem is not None else self.resilience.cfg.request_timeout_s
            fwd_headers = {"content-type": "application/json",
                           "traceparent": span.traceparent(),
                           "x-request-id": req.request_id,
                           HDR_TENANT: req.tenant,
                           # the engine sees the REMAINING budget, not the
                           # client's original: queue wait already spent it
                           HDR_REQUEST_TIMEOUT: f"{budget:.3f}"}
            if prefill is not None:
                fwd_headers[HDR_PREFILLER_HOST_PORT] = prefill.address
            failure = None  # (kind, detail) when this attempt failed retryably
            try:
                resp, target = await self._post_maybe_hedged(
                    req, target, request.path, body, fwd_headers, budget,
                    first_attempt=(attempt == 1))
            except asyncio.TimeoutError:
                failure = ("timeout", f"attempt timeout after {budget:.3f}s")
            except Exception as e:
                failure = ("connect", f"{type(e).__name__}: {e}")
            if failure is None and resp.status in RETRYABLE_STATUSES:
                failure = ("status", f"http {resp.status}")
                resp.release()
            if failure is None:
                break  # response committed (headers in, not retryable)
            kind, detail = failure
            self.metrics.errors.inc()
            self.resilience.on_failure(target.address, reason=detail)
            # every pick ran pre_request: failed attempts still owe producers
            # their post_response so inflight bookkeeping stays balanced
            self.scheduler.post_response(req, target, {"error": detail})
            if attempt >= self.resilience.cfg.retry_max_attempts:
                self.metrics.retries_exhausted.inc()
                self.flight.finish(req.request_id, event="error",
                                   status="error",
                                   reason=f"retries exhausted: {detail}",
                                   http_status=502, attempts=attempt)
                span.set_error(f"retries exhausted: {detail}")
                span.end()
                return web.json_response(
                    {"error": {"message": f"upstream error after {attempt} "
                                          f"attempts: {detail}"}}, status=502)
            self.metrics.retries.labels(reason=kind).inc()
            self.flight.record(req.request_id, "retry", attempt=attempt,
                               endpoint=target.address, reason=detail)
            delay = self.resilience.backoff_s(attempt)
            rem = req.remaining_s()
            if rem is not None:
                delay = min(delay, max(0.0, rem))
            if delay > 0:
                await asyncio.sleep(delay)
            repick = await self._schedule(req, set(excluded))
            if repick.endpoint is None:
                self.flight.finish(req.request_id, event="error",
                                   status="error",
                                   reason=f"no alternate endpoint: {detail}",
                                   http_status=502)
                span.set_error("no alternate endpoint for retry")
                span.end()
                return web.json_response(
                    {"error": {"message": f"upstream error: {detail} "
                                          "(no alternate endpoint)"}},
                    status=502)
            target = repick.endpoint
            prefill = repick.prefill_endpoint
            self._stamp_kv_pull(req, target, body)  # re-plan for the new target
            excluded.add(target.address)
            attempt += 1
            span.set_attribute("llm_d.endpoint", target.address)
            self.flight.record(req.request_id, "routing_decision",
                               endpoint=target.address, retry_attempt=attempt,
                               scores=self._profile_scores(repick))
            self._record_route_decision(req, repick, attempt=attempt)
            self.flight.record(req.request_id, "forward",
                               endpoint=target.address, attempt=attempt)

        echo = {
            "x-llm-d-endpoint": target.address,
            "x-llm-d-request-id": req.request_id,
        }
        if prefill is not None:
            echo[HDR_PREFILLER_HOST_PORT] = prefill.address
        if attempt > 1:
            echo["x-llm-d-attempts"] = str(attempt)

        try:
            if resp.headers.get("Content-Type", "").startswith("text/event-stream"):
                out = web.StreamResponse(
                    status=resp.status,
                    headers={"Content-Type": "text/event-stream", **echo},
                )
                await out.prepare(request)
                t_first = None
                t_last = t_start
                n_chunks = 0
                exemplar = {"trace_id": span.context.trace_id}
                try:
                    async for chunk in resp.content.iter_any():
                        t_last = time.monotonic()
                        if t_first is None:
                            t_first = t_last
                            self.metrics.ttft.observe(t_first - t_start,
                                                      exemplar=exemplar)
                            self._observe_slo(req, "ttft", t_first - t_start)
                        n_chunks += 1
                        await out.write(chunk)
                    await out.write_eof()
                except Exception as e:
                    # Mid-stream failure: the client already holds part of the
                    # stream, so a retry would replay tokens — NEVER retried.
                    # Report the failure (breaker signal) and end the stream.
                    self.metrics.errors.inc()
                    self.resilience.on_failure(target.address,
                                               reason=f"midstream: {e}")
                    self.scheduler.post_response(req, target,
                                                 {"error": str(e)})
                    self.flight.finish(req.request_id, event="error",
                                       status="error", midstream=True,
                                       reason=f"midstream: {e}",
                                       http_status=resp.status,
                                       chunks=n_chunks)
                    span.set_error(f"midstream: {e}")
                    return out
                self._note_outcome(target.address, resp.status)
                info: dict[str, Any] = {"status": resp.status}
                if t_first is not None:
                    info["ttft_ms"] = (t_first - t_start) * 1e3
                    info["e2e_ms"] = (t_last - t_start) * 1e3
                    if n_chunks > 1:  # mean inter-chunk latency ≈ ITL/TPOT sample
                        info["itl_ms"] = (t_last - t_first) * 1e3 / (n_chunks - 1)
                self.scheduler.post_response(req, target, info)
                self.metrics.responses.inc()
                if "e2e_ms" in info:
                    self._observe_e2e(info["e2e_ms"] / 1e3, exemplar=exemplar)
                    self._observe_slo(req, "e2e", info["e2e_ms"] / 1e3)
                self.flight.finish(
                    req.request_id, event="response", status="finished",
                    http_status=resp.status,
                    ttft_ms=(round(info["ttft_ms"], 3)
                             if "ttft_ms" in info else None),
                    streamed=True)
                for k in ("ttft_ms", "e2e_ms", "itl_ms"):
                    if k in info:
                        span.set_attribute(f"llm_d.{k}", round(info[k], 3))
                span.end()
                return out
            try:
                payload = await resp.read()
            except Exception as e:
                # body read failed after committed headers: no retry (the
                # response was already chosen), surface as upstream error
                self.metrics.errors.inc()
                self.resilience.on_failure(target.address, reason=f"read: {e}")
                self.scheduler.post_response(req, target, {"error": str(e)})
                self.flight.finish(req.request_id, event="error",
                                   status="error",
                                   reason=f"upstream read error: {e}",
                                   http_status=502)
                span.set_error(f"read: {e}")
                return web.json_response(
                    {"error": {"message": f"upstream read error: {e}"}},
                    status=502)
            e2e_s = time.monotonic() - t_start
            self._note_outcome(target.address, resp.status)
            self.resilience.note_latency(e2e_s)
            exemplar = {"trace_id": span.context.trace_id}
            self.metrics.ttft.observe(e2e_s, exemplar=exemplar)
            self._observe_slo(req, "ttft", e2e_s)
            info = {"status": resp.status, "e2e_ms": e2e_s * 1e3}
            try:
                usage = json.loads(payload).get("usage", {})
                info["usage"] = usage
                self._account_usage(req, usage)
                if usage.get("completion_tokens"):
                    info["itl_ms"] = e2e_s * 1e3 / usage["completion_tokens"]
            except Exception:
                pass
            self.scheduler.post_response(req, target, info)
            self.metrics.responses.inc()
            self._observe_e2e(e2e_s, exemplar=exemplar)
            self._observe_slo(req, "e2e", e2e_s)
            self.flight.finish(req.request_id, event="response",
                               status="finished", http_status=resp.status)
            span.set_attribute("llm_d.e2e_ms", round(info["e2e_ms"], 3))
            span.set_attribute("http.status_code", resp.status)
            span.end()
            return web.Response(
                body=payload, status=resp.status,
                headers={"Content-Type": "application/json", **echo},
            )
        finally:
            resp.release()
            span.end()  # idempotent backstop for exception exits

    async def _metrics(self, request: web.Request):
        # Registry families (llm_d_epp_*, igw_*) render via the shared
        # formatter; plugin providers (latency predictor, ext-proc, HA) still
        # append their own pre-rendered lines after it.
        lines = [self.registry.expose().rstrip("\n")]
        for plugin in self.scheduler.plugins.values():
            if hasattr(plugin, "prometheus_lines"):
                lines += plugin.prometheus_lines()
        for provider in self.extra_metrics:
            lines += provider()
        return web.Response(text="\n".join(lines) + "\n")

    async def _health(self, request: web.Request):
        return web.json_response({"status": "ok", "endpoints": len(self.pool),
                                  "resilience": self.resilience.snapshot()})

    async def _debug_requests(self, request: web.Request):
        from llmd_tpu.obs.events import debug_list_response

        status, payload = debug_list_response(self.flight,
                                              request.rel_url.query)
        return web.json_response(payload, status=status)

    async def _debug_request(self, request: web.Request):
        from llmd_tpu.obs.events import debug_detail_response

        status, payload = debug_detail_response(self.flight,
                                                request.match_info["rid"])
        return web.json_response(payload, status=status)

    async def _models(self, request: web.Request):
        """Union of /v1/models across the pool, skipping breaker-open,
        draining, and stale endpoints and tolerating per-endpoint failures.
        (Previously the first reachable endpoint answered alone, so a sick
        first endpoint hid every other endpoint's models.)"""
        eps = self.pool.list()
        candidates = [e for e in eps
                      if self.resilience.healthy(e.address) and not e.stale()]
        seen: dict[str, dict] = {}
        for ep in candidates or eps:  # everything filtered: best effort
            try:
                async with self._session.get(
                    f"http://{ep.address}/v1/models",
                    timeout=aiohttp.ClientTimeout(total=2),
                ) as r:
                    if r.status != 200:
                        continue
                    data = await r.json()
            except Exception:
                continue
            for m in data.get("data", []) if isinstance(data, dict) else []:
                mid = m.get("id") if isinstance(m, dict) else None
                if mid is not None and mid not in seen:
                    seen[mid] = m
        return web.json_response({"object": "list",
                                  "data": list(seen.values())})
