"""Predicted-latency EPP plugins.

Parity: reference latency-predictor.md:108-140 — ``predicted-latency-producer``
(predict per candidate, train on completion, streamingMode), ``latency-scorer``
(lowest-latency or SLO-headroom least/most), ``slo-headroom-tier-filter``
(positive/negative tier + exploration), ``latency-slo-admitter`` (shed sheddable
requests no endpoint can serve in SLO). All SLO plugins are no-ops without SLO
headers, so one pipeline serves both traffic kinds.
"""

from __future__ import annotations

import random
from typing import Any, Optional

from llmd_tpu.core.endpoint import Endpoint
from llmd_tpu.core.metrics_contract import StdMetric
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.predictor.client import LocalPredictor, SidecarPredictorClient
from llmd_tpu.predictor.model import LatencySample, heuristic_latency
from llmd_tpu.router.plugins import Admitter, DataProducer, register_plugin
from llmd_tpu.router.scorers import (
    STATE_PREDICTED,
    STATE_PREFIX_HITS,
    STATE_TOKEN_IDS,
    _normalize_inverse,
)

CTX_PREDICTOR = "latency_predictor"
STATE_LATENCY_SAMPLES = "latency_samples"  # endpoint.address → LatencySample


def slo_headroom_ms(req: InferenceRequest, pred: tuple[float, float]) -> Optional[float]:
    """min over the SLOs present of (slo − predicted); None when no SLO headers."""
    ttft, tpot = pred
    hs = []
    if req.slo_ttft_ms is not None:
        hs.append(req.slo_ttft_ms - ttft)
    if req.slo_tpot_ms is not None:
        hs.append(req.slo_tpot_ms - tpot)
    return min(hs) if hs else None


def predicted_e2e_ms(req: InferenceRequest, pred: tuple[float, float]) -> float:
    """E2E estimate from a (ttft_ms, tpot_ms) prediction — the same
    max-tokens extrapolation LatencyScorer ranks by in the no-SLO case. The
    decision ledger (obs/decisions.py) stamps this on ``route_decision`` so
    calibration error can be joined against the observed wall clock."""
    ttft, tpot = pred
    return float(ttft) + float(tpot) * req.sampling.max_tokens


@register_plugin("predicted-latency-producer")
class PredictedLatencyProducer(DataProducer):
    """Predict TTFT/TPOT per candidate; feed observed latencies back as training.

    ``mode``: "local" (in-process model) or "sidecar" (predictUrls/trainUrl).
    ``streamingMode``: false → TTFT trained on e2e latency, TPOT untrained
    (latency-predictor.md:112-118).
    """

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any], mode: str = "local",
                 streamingMode: bool = False, predictUrls: Optional[list[str]] = None,
                 trainUrl: Optional[str] = None, retrainIntervalS: float = 5.0) -> None:
        self.ctx = ctx
        self.streaming_mode = streamingMode
        if CTX_PREDICTOR not in ctx:
            if mode == "sidecar":
                ctx[CTX_PREDICTOR] = SidecarPredictorClient(predictUrls or [], trainUrl)
            else:
                ctx[CTX_PREDICTOR] = LocalPredictor(retrain_interval_s=retrainIntervalS)
        self.predictor = ctx[CTX_PREDICTOR]
        self.stats = {
            "predictions_total": 0, "fallbacks_total": 0, "samples_total": 0,
            "ttft_violations_total": 0, "tpot_violations_total": 0,
            "actual_ttft_sum_ms": 0.0, "predicted_ttft_sum_ms": 0.0, "ttft_obs": 0,
        }

    @staticmethod
    def _sample_for(req: InferenceRequest, e: Endpoint) -> LatencySample:
        n_tokens = len(req.state.get(STATE_TOKEN_IDS) or req.prompt_text().encode())
        hits = req.state.get(STATE_PREFIX_HITS) or {}
        return LatencySample(
            kv_usage=e.metric(StdMetric.KV_UTILIZATION),
            input_len=float(n_tokens),
            queue_depth=e.metric(StdMetric.QUEUED_REQUESTS),
            running_requests=e.metric(StdMetric.RUNNING_REQUESTS),
            prefix_match_pct=hits.get(e.address, 0) / max(1, n_tokens),
            inflight_tokens=e.metric(StdMetric.WAITING_TOKENS),
        )

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None:
        samples = {e.address: self._sample_for(req, e) for e in endpoints}
        preds = self.predictor.predict(list(samples.values()))
        if preds is None:  # predictor cold/unreachable → composite heuristic
            preds = [heuristic_latency(s) for s in samples.values()]
            self.stats["fallbacks_total"] += 1
        self.stats["predictions_total"] += len(preds)
        req.state[STATE_PREDICTED] = dict(zip(samples.keys(), preds))
        req.state[STATE_LATENCY_SAMPLES] = samples

    def post_response(self, req: InferenceRequest, endpoint: Endpoint,
                      response_info: dict[str, Any]) -> None:
        sample = (req.state.get(STATE_LATENCY_SAMPLES) or {}).get(endpoint.address)
        if sample is None:
            return
        if self.streaming_mode:
            sample.ttft_ms = response_info.get("ttft_ms")
            sample.tpot_ms = response_info.get("itl_ms")
        else:
            sample.ttft_ms = response_info.get("e2e_ms")  # e2e-as-TTFT mode
        usage = response_info.get("usage") or {}
        sample.tokens_generated = float(usage.get("completion_tokens", 0))
        if sample.ttft_ms is None and sample.tpot_ms is None:
            return
        self.predictor.record(sample)
        self.stats["samples_total"] += 1
        pred = (req.state.get(STATE_PREDICTED) or {}).get(endpoint.address)
        if pred and sample.ttft_ms is not None:
            self.stats["actual_ttft_sum_ms"] += sample.ttft_ms
            self.stats["predicted_ttft_sum_ms"] += pred[0]
            self.stats["ttft_obs"] += 1
        if req.slo_ttft_ms is not None and sample.ttft_ms is not None \
                and sample.ttft_ms > req.slo_ttft_ms:
            self.stats["ttft_violations_total"] += 1
        if req.slo_tpot_ms is not None and sample.tpot_ms is not None \
                and sample.tpot_ms > req.slo_tpot_ms:
            self.stats["tpot_violations_total"] += 1

    def prometheus_lines(self) -> list[str]:
        s = self.stats
        return [
            f"llm_d_epp_latency_predictions_total {s['predictions_total']}",
            f"llm_d_epp_latency_fallbacks_total {s['fallbacks_total']}",
            f"llm_d_epp_latency_samples_total {s['samples_total']}",
            f"inference_objective_request_ttft_slo_violation_total {s['ttft_violations_total']}",
            f"inference_objective_request_tpot_slo_violation_total {s['tpot_violations_total']}",
            f"inference_objective_request_ttft_seconds_sum {s['actual_ttft_sum_ms'] / 1e3:.6f}",
            f"inference_objective_request_predicted_ttft_seconds_sum {s['predicted_ttft_sum_ms'] / 1e3:.6f}",
            f"inference_objective_request_ttft_seconds_count {s['ttft_obs']}",
        ]


@register_plugin("latency-scorer")
class LatencyScorer:
    """No SLO → lowest predicted latency wins. With SLO → headroom strategy:
    ``least`` bin-packs against the SLO boundary, ``most`` spreads; negative
    headroom always uses least-deficit (latency-predictor.md:128-133)."""

    def __init__(self, headroomSelectionStrategy: str = "least") -> None:
        assert headroomSelectionStrategy in ("least", "most")
        self.strategy = headroomSelectionStrategy

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        preds = req.state.get(STATE_PREDICTED) or {}
        if not preds:
            return {e: 0.0 for e in endpoints}
        if req.slo_ttft_ms is None and req.slo_tpot_ms is None:
            lat = {
                e: preds[e.address][0] + preds[e.address][1] * req.sampling.max_tokens
                for e in endpoints if e.address in preds
            }
            return _normalize_inverse(lat)
        out: dict[Endpoint, float] = {}
        for e in endpoints:
            p = preds.get(e.address)
            if p is None:
                out[e] = 0.0
                continue
            h = slo_headroom_ms(req, p)
            if h is None:
                out[e] = 0.0
            elif h < 0:  # deficit: least-bad, scores in (0, 0.5)
                out[e] = 0.5 / (1.0 + (-h) / 100.0)
            elif self.strategy == "least":  # bin-pack: near boundary, (0.5, 1]
                out[e] = 0.5 + 0.5 / (1.0 + h / 100.0)
            else:  # most: spread, increasing in h, (0.5, 1)
                out[e] = 0.5 + 0.5 * (h / (h + 100.0))
        return out


@register_plugin("slo-headroom-tier-filter")
class SLOHeadroomTierFilter:
    """Positive tier (meets SLO) wins; the negative tier gets explored with
    probability ``exploreNegativeProb`` so recovering pods see traffic."""

    def __init__(self, exploreNegativeProb: float = 0.02) -> None:
        self.explore = exploreNegativeProb

    def filter(self, req: InferenceRequest, endpoints: list[Endpoint]) -> list[Endpoint]:
        if req.slo_ttft_ms is None and req.slo_tpot_ms is None:
            return endpoints  # no-op without SLO headers
        preds = req.state.get(STATE_PREDICTED) or {}
        positive = [
            e for e in endpoints
            if e.address in preds and (slo_headroom_ms(req, preds[e.address]) or -1) >= 0
        ]
        if not positive or random.random() < self.explore:
            return endpoints
        return positive


@register_plugin("latency-slo-admitter")
class LatencySLOAdmitter(Admitter):
    """Reject sheddable requests (priority < 0) that no endpoint can serve within
    SLO — don't spend capacity on a guaranteed miss (latency-predictor.md:136)."""

    def admit(self, req: InferenceRequest, endpoints: list[Endpoint]) -> tuple[bool, str]:
        if req.priority >= 0:
            return True, ""
        if req.slo_ttft_ms is None and req.slo_tpot_ms is None:
            return True, ""
        preds = req.state.get(STATE_PREDICTED) or {}
        if not preds:
            return True, ""
        for e in endpoints:
            p = preds.get(e.address)
            if p is not None and (slo_headroom_ms(req, p) or -1) >= 0:
                return True, ""
        return False, "no endpoint within SLO for sheddable request"
