"""Router resilience layer: deadlines, retries, breakers, drain, hedging.

The reference stack leans on Envoy + gateway health checks to move traffic
off sick pods (ha.py cites exactly this); standalone mode has no Envoy, so
this module is the router's own survival kit:

- **End-to-end deadlines** — the client's ``x-request-timeout`` budget (or
  ``LLMD_REQUEST_TIMEOUT_S`` default) becomes an absolute deadline on the
  InferenceRequest; flow-control wait and scheduling decrement it implicitly,
  each forward attempt uses the remainder as its timeout, and the remainder
  is propagated to the engine under the same header.
- **Bounded retries with jittered exponential backoff** — connect errors,
  attempt timeouts, and 502/503/504 *before the first streamed byte* are
  re-scheduled on a different endpoint (the failed set is excluded from the
  re-pick, like llm-d's ``excluded_runner_ids``). Mid-stream failures are
  never retried: the client already saw bytes, a replay would duplicate them.
- **Per-endpoint circuit breakers with passive health** — forward outcomes
  (and metrics-scrape failures) feed consecutive-failure and failure-rate
  tracking per endpoint; an open breaker filters the endpoint out of
  scheduling, a half-open probe re-admits it after a cooldown. The shape
  follows Envoy's outlier-detection model the reference gateway relies on.
- **Graceful drain** — an endpoint announcing ``draining`` (via its /health,
  observed on breaker probes, or marked administratively) stops being picked
  while its in-flight requests finish.
- **Hedging** (optional) — short non-streaming requests get a second attempt
  on another endpoint after a P99-based delay (Dean & Barroso, "The Tail at
  Scale", CACM 2013); first response wins, the loser is cancelled.

All knobs are env vars (``LLMD_RETRY_*`` / ``LLMD_BREAKER_*`` /
``LLMD_HEDGE_*``), documented in observability/resilience.md and
deploy/ENV_VARS.md.

Threading: the scheduler runs on its own worker thread while forward
outcomes land on the asyncio loop, so the manager takes a threading.Lock
around all breaker state.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Iterable, List, Optional

from llmd_tpu.core.endpoint import Endpoint

__all__ = [
    "BreakerState",
    "EndpointBreaker",
    "ResilienceConfig",
    "ResilienceManager",
    "RETRYABLE_STATUSES",
]

# Gateway-retryable upstream statuses: the request never reached a healthy
# serving path, so a replay on another endpoint is safe and invisible.
RETRYABLE_STATUSES = frozenset({502, 503, 504})


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class ResilienceConfig:
    """Knob set for the whole layer (see observability/resilience.md)."""

    # deadlines
    request_timeout_s: float = 600.0  # default budget when no header arrives
    # retries
    retry_max_attempts: int = 3  # total attempts (1 initial + N-1 retries)
    retry_backoff_ms: float = 25.0  # base of the exponential schedule
    retry_backoff_max_ms: float = 1000.0
    # breaker
    breaker_consecutive_failures: int = 5
    breaker_failure_rate: float = 0.5  # open when window rate exceeds this
    breaker_window: int = 20  # sliding window of recent outcomes
    breaker_min_volume: int = 10  # rate check needs at least this many samples
    breaker_cooldown_s: float = 5.0  # open → half-open delay
    breaker_half_open_successes: int = 2  # probe successes required to close
    # hedging
    hedge_enabled: bool = False
    hedge_delay_ms: float = 0.0  # 0 = auto (observed P99 of non-streaming e2e)
    hedge_max_tokens: int = 32  # only hedge short generations

    @classmethod
    def from_env(cls) -> "ResilienceConfig":
        return cls(
            request_timeout_s=_env_f("LLMD_REQUEST_TIMEOUT_S", 600.0),
            retry_max_attempts=max(1, _env_i("LLMD_RETRY_MAX_ATTEMPTS", 3)),
            retry_backoff_ms=_env_f("LLMD_RETRY_BACKOFF_MS", 25.0),
            retry_backoff_max_ms=_env_f("LLMD_RETRY_BACKOFF_MAX_MS", 1000.0),
            breaker_consecutive_failures=max(
                1, _env_i("LLMD_BREAKER_CONSECUTIVE_FAILURES", 5)),
            breaker_failure_rate=_env_f("LLMD_BREAKER_FAILURE_RATE", 0.5),
            breaker_window=max(1, _env_i("LLMD_BREAKER_WINDOW", 20)),
            breaker_min_volume=max(1, _env_i("LLMD_BREAKER_MIN_VOLUME", 10)),
            breaker_cooldown_s=_env_f("LLMD_BREAKER_COOLDOWN_S", 5.0),
            breaker_half_open_successes=max(
                1, _env_i("LLMD_BREAKER_HALF_OPEN_SUCCESSES", 2)),
            hedge_enabled=os.environ.get("LLMD_HEDGE_ENABLED", "0")
            not in ("0", "", "false", "False"),
            hedge_delay_ms=_env_f("LLMD_HEDGE_DELAY_MS", 0.0),
            hedge_max_tokens=_env_i("LLMD_HEDGE_MAX_TOKENS", 32),
        )


class BreakerState(str, Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class EndpointBreaker:
    """One endpoint's outlier-ejection state. Mutated only under the
    manager's lock — no locking of its own."""

    __slots__ = ("state", "consecutive_failures", "window", "opened_at",
                 "open_until", "half_open_successes", "half_open_inflight",
                 "probe_admitted_at", "open_count")

    def __init__(self) -> None:
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.window: list = []  # recent outcomes, True = failure
        self.opened_at = 0.0
        self.open_until = 0.0
        self.half_open_successes = 0
        self.half_open_inflight = 0
        self.probe_admitted_at = 0.0
        self.open_count = 0  # lifetime opens (for snapshots)

    def _note(self, failed: bool, window: int) -> None:
        self.window.append(failed)
        if len(self.window) > window:
            del self.window[: len(self.window) - window]

    def failure_rate(self) -> float:
        if not self.window:
            return 0.0
        return sum(self.window) / len(self.window)


class ResilienceManager:
    """Shared breaker/drain/hedge state + the retry policy.

    The scheduler consults :meth:`filter_endpoints` on every pick; the router
    proxy reports attempt outcomes through :meth:`on_success` /
    :meth:`on_failure`; the metrics poller feeds scrape failures in as a
    passive health signal via :meth:`note_scrape_error`.
    """

    def __init__(self, cfg: Optional[ResilienceConfig] = None,
                 metrics=None, flight=None) -> None:
        self.cfg = cfg or ResilienceConfig.from_env()
        self.metrics = metrics  # RouterMetrics (may be None in unit tests)
        self.flight = flight  # FlightRecorder (system events)
        self._lock = threading.Lock()
        self._breakers: dict[str, EndpointBreaker] = {}
        self._draining: set[str] = set()
        # reservoir of recent non-streaming e2e latencies for the auto hedge
        # delay (ring of 256 keeps the P99 tracking the current regime)
        self._latencies: list[float] = []
        self._lat_idx = 0
        self._rng = random.Random(0xC1BC)

    # ------------------------------------------------------------- breakers
    def _breaker(self, address: str) -> EndpointBreaker:
        br = self._breakers.get(address)
        if br is None:
            br = self._breakers[address] = EndpointBreaker()
        return br

    def _transition(self, address: str, br: EndpointBreaker,
                    state: BreakerState, reason: str = "") -> None:
        prev, br.state = br.state, state
        if state is BreakerState.OPEN and prev is not BreakerState.OPEN:
            br.opened_at = time.monotonic()
            br.open_until = br.opened_at + self.cfg.breaker_cooldown_s
            br.open_count += 1
            br.half_open_successes = 0
            if self.metrics is not None:
                self.metrics.breaker_opens.inc()
            if self.flight is not None:
                self.flight.record_system("breaker_open", endpoint=address,
                                          reason=reason or None,
                                          consecutive=br.consecutive_failures,
                                          failure_rate=round(br.failure_rate(), 3))
        elif state is BreakerState.CLOSED and prev is not BreakerState.CLOSED:
            br.consecutive_failures = 0
            br.window.clear()
            br.half_open_successes = 0
            br.half_open_inflight = 0
            if self.metrics is not None:
                self.metrics.breaker_closes.inc()
            if self.flight is not None:
                self.flight.record_system(
                    "breaker_close", endpoint=address,
                    open_ms=round((time.monotonic() - br.opened_at) * 1e3, 1))

    def allow(self, address: str, now: Optional[float] = None) -> bool:
        """May this endpoint receive a request right now? An expired-cooldown
        OPEN breaker transitions to HALF_OPEN and admits a single probe."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if address in self._draining:
                return False
            br = self._breakers.get(address)
            if br is None or br.state is BreakerState.CLOSED:
                return True
            if br.state is BreakerState.OPEN:
                if now < br.open_until:
                    return False
                br.state = BreakerState.HALF_OPEN
                br.half_open_inflight = 0
            # HALF_OPEN: one probe in flight at a time. The slot expires after
            # a cooldown — filter_endpoints() consumes it for every pick the
            # endpoint is merely a CANDIDATE in, and when the scheduler then
            # chooses someone else no outcome ever lands here to release it.
            # Without the expiry that stale slot ejects the endpoint forever.
            if (br.half_open_inflight >= 1
                    and now - br.probe_admitted_at < self.cfg.breaker_cooldown_s):
                return False
            br.half_open_inflight = 1
            br.probe_admitted_at = now
            return True

    def on_success(self, address: str) -> None:
        with self._lock:
            br = self._breakers.get(address)
            if br is None:
                return
            br.consecutive_failures = 0
            br._note(False, self.cfg.breaker_window)
            if br.state is BreakerState.HALF_OPEN:
                br.half_open_inflight = max(0, br.half_open_inflight - 1)
                br.half_open_successes += 1
                if br.half_open_successes >= self.cfg.breaker_half_open_successes:
                    self._transition(address, br, BreakerState.CLOSED)

    def on_failure(self, address: str, reason: str = "") -> None:
        with self._lock:
            br = self._breaker(address)
            br.consecutive_failures += 1
            br._note(True, self.cfg.breaker_window)
            if br.state is BreakerState.HALF_OPEN:
                # failed probe: straight back to OPEN for another cooldown
                br.half_open_inflight = max(0, br.half_open_inflight - 1)
                br.state = BreakerState.OPEN  # suppress re-open event spam
                br.open_until = time.monotonic() + self.cfg.breaker_cooldown_s
                return
            if br.state is BreakerState.CLOSED and (
                br.consecutive_failures >= self.cfg.breaker_consecutive_failures
                or (len(br.window) >= self.cfg.breaker_min_volume
                    and br.failure_rate() >= self.cfg.breaker_failure_rate)
            ):
                self._transition(address, br, BreakerState.OPEN, reason=reason)

    def forget(self, address: str) -> None:
        """Drop every trace of an endpoint that left discovery. Replica
        churn (pool scale cycles) would otherwise grow the breaker map and
        the draining set without bound — and a re-used address would
        inherit a dead replica's open breaker."""
        with self._lock:
            self._breakers.pop(address, None)
            self._draining.discard(address)

    def note_scrape_error(self, address: str) -> None:
        """Metrics-scrape failure: a passive health signal. An endpoint whose
        /metrics stops answering is almost always one whose serving path is
        about to stop answering too — feeding the breaker here ejects it
        BEFORE a client request has to pay for the discovery."""
        self.on_failure(address, reason="scrape_error")

    # --------------------------------------------------------------- drain
    def set_draining(self, address: str, draining: bool = True) -> None:
        with self._lock:
            if draining:
                self._draining.add(address)
            else:
                self._draining.discard(address)

    def is_draining(self, address: str) -> bool:
        with self._lock:
            return address in self._draining

    def healthy(self, address: str) -> bool:
        """Non-mutating view for read-only consumers (/v1/models aggregation):
        not draining and breaker not currently OPEN. Unlike :meth:`allow`
        this never admits a half-open probe — listing models must not
        consume the one probe slot a recovering endpoint gets."""
        now = time.monotonic()
        with self._lock:
            if address in self._draining:
                return False
            br = self._breakers.get(address)
            if br is None:
                return True
            return not (br.state is BreakerState.OPEN and now < br.open_until)

    # ---------------------------------------------------------- scheduling
    def filter_endpoints(self, endpoints: Iterable[Endpoint]) -> List[Endpoint]:
        """Scheduling-time filter: drop breaker-open and draining endpoints.

        Fail-open: if the filter would empty the candidate set (every breaker
        open — e.g. the fault is actually downstream of the pool), the
        original set is returned so the pool never self-ejects entirely
        (Envoy's max_ejection_percent backstop)."""
        eps = list(endpoints)
        allowed = [e for e in eps if self.allow(e.address)]
        return allowed if allowed else eps

    def open_endpoints(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return [a for a, br in self._breakers.items()
                    if br.state is BreakerState.OPEN and now < br.open_until]

    def attempt_states(self, addresses: Iterable[str]) -> dict[str, dict]:
        """Per-address breaker view for the routing decision ledger
        (obs/decisions.py): only non-pristine entries are reported, so the
        ledger records WHY resilience dropped candidates without bloating
        the common all-healthy case."""
        out: dict[str, dict] = {}
        with self._lock:
            for a in addresses:
                br = self._breakers.get(a)
                draining = a in self._draining
                if br is None and not draining:
                    continue
                if br is not None and br.state is BreakerState.CLOSED \
                        and not br.consecutive_failures and not draining:
                    continue
                entry: dict = {}
                if br is not None:
                    entry["state"] = br.state.value
                    if br.consecutive_failures:
                        entry["consecutive_failures"] = br.consecutive_failures
                if draining:
                    entry["draining"] = True
                out[a] = entry
        return out

    def snapshot(self) -> dict:
        """Breaker/drain state for /health and debugging."""
        with self._lock:
            return {
                "breakers": {
                    a: {"state": br.state.value,
                        "consecutive_failures": br.consecutive_failures,
                        "failure_rate": round(br.failure_rate(), 3),
                        "open_count": br.open_count}
                    for a, br in self._breakers.items()
                    if br.state is not BreakerState.CLOSED or br.window
                },
                "draining": sorted(self._draining),
            }

    # -------------------------------------------------------------- retries
    def retryable_status(self, status: int) -> bool:
        return status in RETRYABLE_STATUSES

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff for retry ``attempt`` (1-based):
        uniform in (0, min(base * 2^(attempt-1), max)]."""
        cap = self.cfg.retry_backoff_max_ms / 1e3
        span = min(cap, self.cfg.retry_backoff_ms / 1e3 * (2 ** max(0, attempt - 1)))
        with self._lock:
            return self._rng.uniform(0, span)

    # -------------------------------------------------------------- hedging
    def note_latency(self, seconds: float) -> None:
        """Feed one non-streaming e2e sample into the hedge-delay reservoir."""
        with self._lock:
            if len(self._latencies) < 256:
                self._latencies.append(seconds)
            else:
                self._latencies[self._lat_idx % 256] = seconds
            self._lat_idx += 1

    def hedge_delay_s(self) -> float:
        """Delay before firing the hedged attempt: the configured value, or
        the observed P99 of recent non-streaming e2e (min 50 ms until enough
        samples accumulate — hedging against noise wastes capacity)."""
        if self.cfg.hedge_delay_ms > 0:
            return self.cfg.hedge_delay_ms / 1e3
        with self._lock:
            lats = sorted(self._latencies)
        if len(lats) < 20:
            return 0.05
        return max(0.05, lats[int(len(lats) * 0.99)])

    def hedge_eligible(self, req) -> bool:
        """Hedge only short non-streaming requests: duplicated work must be
        cheap, and streaming replays would duplicate client-visible bytes."""
        return (self.cfg.hedge_enabled
                and not req.streaming
                and req.sampling.max_tokens <= self.cfg.hedge_max_tokens)
