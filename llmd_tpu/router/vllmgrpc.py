"""vllmgrpc parser front: the router's gRPC serving surface (R3 parity).

The reference's EPP ships a ``vllmgrpc-parser`` handling the vLLM gRPC API's
``Generate`` and ``Embed`` methods (request-handling.md:74). This module is
that front for the TPU stack: a gRPC service (clean-room proto subset,
protos/vllm_grpc.proto) that parses each RPC into an ``InferenceRequest``,
runs the SAME admission pipeline as the HTTP and ext-proc fronts (flow
control → async producers → Filter/Score/Pick), then proxies to the picked
pod's OpenAI HTTP API and translates the answer back to protobuf — gRPC
clients get scheduler-quality routing without the pods growing a gRPC port.

Same generic-handler wiring as extproc.py (grpcio-tools isn't in the image, so
no generated service stubs — the method handlers register explicitly under the
full service name).
"""

from __future__ import annotations

import asyncio
import json
from concurrent import futures
from typing import Optional

import aiohttp
import grpc

from llmd_tpu.router import vllm_grpc_pb2 as pb
from llmd_tpu.router.server import RouterServer

SERVICE = "llmd.vllmgrpc.v1.VllmService"


class VllmGrpcFront:
    """gRPC front sharing one RouterServer's scheduling plane."""

    def __init__(self, router: RouterServer, host: str = "127.0.0.1",
                 port: int = 0, max_rpcs: int = 64) -> None:
        self.router = router
        self.host, self.port = host, port
        self.max_rpcs = max_rpcs
        self._server: Optional[grpc.Server] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.metrics = {"generate_total": 0, "embed_total": 0, "errors_total": 0}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        """Await from the router's loop (admission is loop-bound)."""
        self._loop = asyncio.get_running_loop()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_rpcs,
                                       thread_name_prefix="vllmgrpc"),
            maximum_concurrent_rpcs=self.max_rpcs,
        )
        handlers = {
            "Generate": grpc.unary_stream_rpc_method_handler(
                self._generate,
                request_deserializer=pb.GenerateRequest.FromString,
                response_serializer=pb.GenerateResponse.SerializeToString),
            "Embed": grpc.unary_unary_rpc_method_handler(
                self._embed,
                request_deserializer=pb.EmbedRequest.FromString,
                response_serializer=pb.EmbedResponse.SerializeToString),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)

    # -- helpers -----------------------------------------------------------
    def _await(self, coro, timeout: float = 600.0):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(timeout)

    def _parse(self, path: str, body: dict) -> "object":
        # one parser, one admission semantics with the HTTP front
        return self.router.prepare_request(path, body, {})

    @staticmethod
    def _code_for(err) -> grpc.StatusCode:
        """Rejection → gRPC status. 429 sheds map to RESOURCE_EXHAUSTED so
        standard client retry policy backs off instead of hammering."""
        return (grpc.StatusCode.RESOURCE_EXHAUSTED if err.status == 429
                else grpc.StatusCode.UNAVAILABLE)

    @staticmethod
    def _fwd_headers(ireq, result) -> dict:
        from llmd_tpu.core.request import HDR_PREFILLER_HOST_PORT

        hdrs = {"x-request-id": ireq.request_id}
        if result.prefill_endpoint is not None:
            # P/D disaggregation rides this header through the pod's sidecar —
            # dropping it silently degrades gRPC traffic to aggregated serving
            hdrs[HDR_PREFILLER_HOST_PORT] = result.prefill_endpoint.address
        return hdrs

    async def _post_json(self, url: str, body: dict, headers: dict) -> dict:
        async with self.router._session.post(
            url, json=body, headers=headers,
            timeout=aiohttp.ClientTimeout(total=600)) as resp:
            text = await resp.text()
            if resp.status != 200:
                raise RuntimeError(f"upstream HTTP {resp.status}: {text[:200]}")
            return json.loads(text)

    # -- RPCs --------------------------------------------------------------
    def _generate(self, req: pb.GenerateRequest, context):
        self.metrics["generate_total"] += 1
        body: dict = {
            "model": req.model,
            "max_tokens": int(req.sampling_params.max_tokens or 16),
            "temperature": float(req.sampling_params.temperature),
        }
        if req.sampling_params.top_p:
            body["top_p"] = float(req.sampling_params.top_p)
        if req.sampling_params.top_k:
            body["top_k"] = int(req.sampling_params.top_k)
        if req.sampling_params.ignore_eos:
            body["ignore_eos"] = True
        if req.sampling_params.stop:
            body["stop"] = list(req.sampling_params.stop)
        if req.lora_adapter:
            body["lora_adapter"] = req.lora_adapter
        if req.WhichOneof("input") == "prompt_token_ids":
            body["prompt_token_ids"] = list(req.prompt_token_ids.values)
        else:
            body["prompt"] = req.prompt

        ireq = self._parse("/v1/completions", body)
        import time

        t0 = time.monotonic()
        try:
            result, err = self._await(self.router.admit_and_schedule(ireq))
        except Exception as e:
            self.metrics["errors_total"] += 1
            context.abort(grpc.StatusCode.INTERNAL, f"EPP error: {e}")
            return
        if err is not None:
            self.metrics["errors_total"] += 1
            context.abort(self._code_for(err), err.message)
            return
        target = result.endpoint
        rid = req.request_id or ireq.request_id
        hdrs = self._fwd_headers(ireq, result)

        if req.stream:
            yield from self._generate_streaming(req, body, ireq, result, rid,
                                                hdrs, context, t0)
            return
        try:
            out = self._await(self._post_json(
                f"http://{target.address}/v1/completions", body, hdrs))
        except Exception as e:
            self.metrics["errors_total"] += 1
            self.router.scheduler.post_response(
                ireq, target, {"status": 502, "error": str(e),
                               "e2e_ms": (time.monotonic() - t0) * 1e3})
            context.abort(grpc.StatusCode.UNAVAILABLE, f"upstream: {e}")
            return
        usage = out.get("usage", {})
        # the same response_info shape the HTTP front feeds the latency/SLO
        # producers — gRPC traffic trains the predictor like any other
        self.router.scheduler.post_response(ireq, target, {
            "status": 200, "usage": usage,
            "e2e_ms": (time.monotonic() - t0) * 1e3})
        choice = (out.get("choices") or [{}])[0]
        yield pb.GenerateResponse(
            request_id=rid,
            outputs=[pb.Completion(text=choice.get("text", ""),
                                   finish_reason=choice.get("finish_reason") or "")],
            finished=True,
            usage=pb.UsageInfo(
                prompt_tokens=int(usage.get("prompt_tokens", 0)),
                completion_tokens=int(usage.get("completion_tokens", 0)),
                cached_tokens=int(usage.get("cached_tokens", 0))),
            endpoint=target.address,
        )

    def _generate_streaming(self, req, body, ireq, result, rid, hdrs,
                            context, t0):
        """stream=true: bridge the upstream SSE stream into the gRPC stream —
        each data: chunk becomes one incremental GenerateResponse."""
        import time

        target = result.endpoint
        agen = self._sse_chunks(
            f"http://{target.address}/v1/completions", dict(body, stream=True),
            hdrs)
        usage: dict = {}
        try:
            while True:
                chunk = self._await(agen.__anext__())
                if chunk is None:
                    break
                choice = (chunk.get("choices") or [{}])[0]
                usage = chunk.get("usage") or usage
                yield pb.GenerateResponse(
                    request_id=rid,
                    outputs=[pb.Completion(
                        text=choice.get("text", ""),
                        finish_reason=choice.get("finish_reason") or "")],
                    finished=bool(choice.get("finish_reason")),
                    endpoint=target.address,
                )
        except StopAsyncIteration:
            pass
        except Exception as e:
            self.metrics["errors_total"] += 1
            self.router.scheduler.post_response(
                ireq, target, {"status": 502, "error": str(e),
                               "e2e_ms": (time.monotonic() - t0) * 1e3})
            context.abort(grpc.StatusCode.UNAVAILABLE, f"upstream: {e}")
            return
        self.router.scheduler.post_response(ireq, target, {
            "status": 200, "usage": usage,
            "e2e_ms": (time.monotonic() - t0) * 1e3})

    async def _sse_chunks(self, url: str, body: dict, headers: dict):
        """Async generator over the upstream SSE data: events (None at [DONE])."""
        async with self.router._session.post(
            url, json=body, headers=headers,
            timeout=aiohttp.ClientTimeout(total=600)) as resp:
            if resp.status != 200:
                raise RuntimeError(f"upstream HTTP {resp.status}")
            async for raw in resp.content:
                line = raw.decode(errors="replace").strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    yield None
                    return
                try:
                    yield json.loads(payload)
                except json.JSONDecodeError:
                    continue
        yield None

    def _embed(self, req: pb.EmbedRequest, context):
        import time

        self.metrics["embed_total"] += 1
        body = {"model": req.model, "input": req.input}
        ireq = self._parse("/v1/embeddings", body)
        t0 = time.monotonic()
        try:
            result, err = self._await(self.router.admit_and_schedule(ireq))
        except Exception as e:
            self.metrics["errors_total"] += 1
            context.abort(grpc.StatusCode.INTERNAL, f"EPP error: {e}")
        if err is not None:
            self.metrics["errors_total"] += 1
            context.abort(self._code_for(err), err.message)
        target = result.endpoint
        try:
            out = self._await(self._post_json(
                f"http://{target.address}/v1/embeddings", body,
                self._fwd_headers(ireq, result)))
        except Exception as e:
            self.metrics["errors_total"] += 1
            # release the inflight counters pre_request incremented
            self.router.scheduler.post_response(
                ireq, target, {"status": 502, "error": str(e),
                               "e2e_ms": (time.monotonic() - t0) * 1e3})
            context.abort(grpc.StatusCode.UNAVAILABLE, f"upstream: {e}")
        self.router.scheduler.post_response(ireq, target, {
            "status": 200, "usage": out.get("usage", {}),
            "e2e_ms": (time.monotonic() - t0) * 1e3})
        emb = (out.get("data") or [{}])[0].get("embedding", [])
        return pb.EmbedResponse(request_id=req.request_id or ireq.request_id,
                                embedding=emb, endpoint=target.address)
