"""Filter→Score→Pick scheduler + profile handlers.

Parity: reference epp/scheduling.md:7-68 (weighted score sum per profile, picker),
:110-118 (single-profile / disagg-profile handlers) and
disaggregation/README.md:50-93 (decode-first decide-then-prefill flow with the
uncached-suffix decider).
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from llmd_tpu.core.config import FrameworkConfig
from llmd_tpu.core.endpoint import Endpoint, EndpointPool
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.router.plugins import (
    Admitter,
    DataProducer,
    Filter,
    Picker,
    Scorer,
    build_plugin,
)
from llmd_tpu.obs.decisions import decisions_enabled
from llmd_tpu.router.scorers import (
    STATE_PREDICTED,
    STATE_PREFIX_HITS,
    STATE_TOKEN_IDS,
    clamp_scores,
)


@dataclass
class ProfileRun:
    name: str
    endpoint: Optional[Endpoint]
    scores: dict[Endpoint, float] = field(default_factory=dict)
    # Decision-ledger capture (obs/decisions.py): {"filters": [[name, dropped]],
    # "candidates": n, "tie": n, "scorers": [(name, weight, {Endpoint: score})]}.
    # None whenever LLMD_DECISION_LEDGER is off — the detail path then
    # allocates nothing.
    detail: Optional[dict] = None


@dataclass
class SchedulingResult:
    """Primary endpoint + optional prefill endpoint (P/D) + per-profile detail."""

    endpoint: Optional[Endpoint]
    prefill_endpoint: Optional[Endpoint] = None
    profiles: dict[str, ProfileRun] = field(default_factory=dict)
    rejected: Optional[str] = None
    latency_s: float = 0.0
    # Candidates removed before any profile ran ({"excluded": n,
    # "resilience_dropped": n}); None when the decision ledger is off.
    pre_drops: Optional[dict] = None
    # Disagg decider outcome (docs/pd-disaggregation.md): decision, reason,
    # predicted TTFT deltas, priced kv_pull hop, and the chosen P/D pair.
    # None outside the disagg-profile-handler.
    pd: Optional[dict] = None


class Profile:
    def __init__(self, name: str, entries: list[tuple[Any, float]]) -> None:
        self.name = name
        self.filters: list[Filter] = []
        self.scorers: list[tuple[Scorer, float]] = []
        self.picker: Optional[Picker] = None
        for plugin, weight in entries:
            if hasattr(plugin, "filter"):
                self.filters.append(plugin)
            elif hasattr(plugin, "score"):
                self.scorers.append((plugin, weight))
            elif hasattr(plugin, "pick"):
                self.picker = plugin

    def run(self, req: InferenceRequest, endpoints: list[Endpoint],
            detail: bool = False) -> ProfileRun:
        cands = list(endpoints)
        drops: Optional[list] = [] if detail else None
        for f in self.filters:
            kept = f.filter(req, cands)
            if detail and len(kept) != len(cands):
                drops.append([type(f).__name__, len(cands) - len(kept)])
            cands = kept
            if not cands:
                det = ({"filters": drops, "candidates": 0, "tie": 0,
                        "scorers": []} if detail else None)
                return ProfileRun(self.name, None, detail=det)
        totals: dict[Endpoint, float] = {e: 0.0 for e in cands}
        per_scorer: Optional[list] = [] if detail else None
        for scorer, weight in self.scorers:
            scores = clamp_scores(scorer.score(req, cands), totals)
            for e, s in scores.items():
                totals[e] += weight * s
            if detail:
                per_scorer.append((type(scorer).__name__, weight, scores))
        picked = self.picker.pick(req, totals) if self.picker else None
        det = None
        if detail:
            mx = max(totals.values()) if totals else 0.0
            det = {
                "filters": drops,
                "candidates": len(totals),
                "tie": sum(1 for s in totals.values() if s >= mx - 1e-9),
                "scorers": per_scorer,
            }
        return ProfileRun(self.name, picked, totals, det)


class Scheduler:
    """Built from a FrameworkConfig; owns plugin instances and the shared context."""

    def __init__(self, config: FrameworkConfig, pool: EndpointPool,
                 ctx: Optional[dict[str, Any]] = None) -> None:
        self.config = config
        self.pool = pool
        self.ctx = ctx if ctx is not None else {}
        self.plugins: dict[str, Any] = {}
        for spec in config.plugins:
            self.plugins[spec.name] = build_plugin(spec.type, spec.params, self.ctx)
        self.profiles: dict[str, Profile] = {}
        for prof in config.scheduling_profiles:
            entries = [(self.plugins[r.plugin_ref], r.weight) for r in prof.plugins]
            self.profiles[prof.name] = Profile(prof.name, entries)
        self.producers: list[DataProducer] = [
            p for p in self.plugins.values() if isinstance(p, DataProducer)
        ]
        self.admitters: list[Admitter] = [
            p for p in self.plugins.values() if isinstance(p, Admitter)
        ]
        self.handler = config.profile_handler
        # disagg decider params (docs/pd-disaggregation.md): the config's
        # uncachedSuffixThreshold wins; LLMD_PD_THRESHOLD_TOKENS is the env
        # fallback when the config leaves it unset. The kv_pull hop price
        # (base + per-block transfer cost) and the decision margin gate the
        # predictor comparison in _pd_decide.
        raw_fc = config.raw.get("disaggregation", {}) or {}
        self.pd_threshold_tokens = int(raw_fc.get(
            "uncachedSuffixThreshold",
            os.environ.get("LLMD_PD_THRESHOLD_TOKENS", "0") or 0))
        self.pd_kv_pull_base_ms = float(
            os.environ.get("LLMD_PD_KV_PULL_BASE_MS", "2.0"))
        self.pd_kv_pull_ms_per_block = float(
            os.environ.get("LLMD_PD_KV_PULL_MS_PER_BLOCK", "0.5"))
        self.pd_margin_ms = float(os.environ.get("LLMD_PD_MARGIN_MS", "0.0"))
        self.metrics = {"scheduled_total": 0, "rejected_total": 0,
                        "pd_splits_total": 0, "pd_aggregated_total": 0}
        # Resilience hook (router/resilience.py): filters breaker-open and
        # draining endpoints out of every pick. None = no filtering.
        self.endpoint_filter: Optional[Callable[[list[Endpoint]], list[Endpoint]]] = None
        # Decision-ledger switch, read once: when off, Profile.run skips all
        # detail capture and schedule() allocates nothing extra per request.
        self.record_decisions = decisions_enabled()

    # ------------------------------------------------------------------
    def schedule(self, req: InferenceRequest,
                 exclude: Optional[set[str]] = None) -> SchedulingResult:
        """Pick endpoint(s) for ``req``. ``exclude`` holds addresses already
        tried this request (retry re-pick, llm-d ``excluded_runner_ids``
        semantics) — they are removed BEFORE the resilience filter so the
        fail-open backstop cannot hand back an endpoint that just failed."""
        t0 = time.monotonic()
        endpoints = self.pool.list()
        n_pool = len(endpoints)
        if exclude:
            endpoints = [e for e in endpoints if e.address not in exclude]
        n_after_exclude = len(endpoints)
        if self.endpoint_filter is not None and endpoints:
            endpoints = self.endpoint_filter(endpoints)
        if not endpoints:
            return SchedulingResult(None, rejected="no endpoints")
        pre_drops = None
        if self.record_decisions:
            n_excluded = n_pool - n_after_exclude
            n_resilience = n_after_exclude - len(endpoints)
            if n_excluded or n_resilience:
                pre_drops = {"excluded": n_excluded,
                             "resilience_dropped": n_resilience}
        for p in self.producers:
            p.produce(req, endpoints)
        for a in self.admitters:
            ok, why = a.admit(req, endpoints)
            if not ok:
                self.metrics["rejected_total"] += 1
                return SchedulingResult(None, rejected=why or "admission rejected")

        if self.handler == "disagg-profile-handler":
            res = self._schedule_disagg(req, endpoints)
        else:
            res = self._schedule_single(req, endpoints)
        res.pre_drops = pre_drops

        if res.endpoint is not None:
            self.metrics["scheduled_total"] += 1
            for p in self.producers:
                p.pre_request(req, res.endpoint)
            nh = self.plugins.get("no-hit-lru-scorer")
            if nh is not None and hasattr(nh, "note_pick"):
                hits = req.state.get(STATE_PREFIX_HITS) or {}
                if not any(v > 0 for v in hits.values()):
                    nh.note_pick(res.endpoint)
        res.latency_s = time.monotonic() - t0
        return res

    def post_response(self, req: InferenceRequest, endpoint: Endpoint,
                      response_info: dict[str, Any]) -> None:
        for p in self.producers:
            p.post_response(req, endpoint, response_info)

    # ------------------------------------------------------------------
    def _profile(self, name: str) -> Optional[Profile]:
        return self.profiles.get(name)

    def _schedule_single(self, req, endpoints) -> SchedulingResult:
        prof = self._profile("default") or next(iter(self.profiles.values()), None)
        if prof is None:
            return SchedulingResult(None, rejected="no scheduling profile")
        run = prof.run(req, endpoints, detail=self.record_decisions)
        return SchedulingResult(run.endpoint, profiles={prof.name: run},
                                rejected=None if run.endpoint else "no endpoint passed filters")

    def _schedule_disagg(self, req, endpoints) -> SchedulingResult:
        """Decode profile first; predictor-gated decider; maybe prefill profile.

        Reference disaggregation/README.md:57-91: run decode profile → compute
        the uncached suffix on the chosen D endpoint → if large enough, run the
        prefill profile and consult the latency predictor: disaggregate only
        when predicted TTFT-on-P plus the priced kv_pull hop beats aggregated
        prefill on D. Short/cached prompts skip the hop before the prefill
        profile ever runs. The outcome (decision, reason, predicted deltas,
        chosen P/D pair) is stamped into ``result.pd`` for the decision ledger.
        """
        dec_prof = self._profile("decode") or self._profile("default")
        if dec_prof is None:
            return SchedulingResult(None, rejected="no decode profile")
        dec = dec_prof.run(req, endpoints, detail=self.record_decisions)
        if dec.endpoint is None:
            return SchedulingResult(None, rejected="no decode endpoint")
        result = SchedulingResult(dec.endpoint, profiles={dec_prof.name: dec})

        hits = req.state.get(STATE_PREFIX_HITS) or {}
        n_tokens = len(req.state.get(STATE_TOKEN_IDS) or req.prompt_text().encode())
        uncached = n_tokens - hits.get(dec.endpoint.address, 0)
        pre_prof = self._profile("prefill")
        if pre_prof is None:
            result.pd = self._pd_aggregated(req, dec.endpoint,
                                            "no_prefill_profile", uncached)
            return result
        if uncached < self.pd_threshold_tokens:
            # short uncached suffix: decode-only (aggregated), hop skipped
            result.pd = self._pd_aggregated(req, dec.endpoint,
                                            "short_uncached_suffix", uncached)
            return result
        pre = pre_prof.run(req, [e for e in endpoints if e != dec.endpoint] or endpoints,
                           detail=self.record_decisions)
        if pre.endpoint is None:
            result.pd = self._pd_aggregated(req, dec.endpoint,
                                            "no_prefill_endpoint", uncached)
            return result
        result.profiles[pre_prof.name] = pre
        result.pd = self._pd_decide(req, dec.endpoint, pre.endpoint, uncached)
        if result.pd["decision"] == "split":
            result.prefill_endpoint = pre.endpoint
            self.metrics["pd_splits_total"] += 1
        else:
            self.metrics["pd_aggregated_total"] += 1
        return result

    # ------------------------------------------------------------ pd decider
    def _pd_hop_ms(self, uncached: int) -> float:
        """Priced kv_pull hop: P→D transfer of the uncached suffix's blocks."""
        blocks = math.ceil(max(0, uncached) / 16)
        return self.pd_kv_pull_base_ms + self.pd_kv_pull_ms_per_block * blocks

    def _pd_aggregated(self, req, dec_ep, reason: str, uncached: int) -> dict:
        self.metrics["pd_aggregated_total"] += 1
        pd = {"decision": "aggregated", "reason": reason,
              "uncached_tokens": uncached,
              "hop_ms": round(self._pd_hop_ms(uncached), 3),
              "decode": dec_ep.address}
        pred = (req.state.get(STATE_PREDICTED) or {}).get(dec_ep.address)
        if pred is not None:
            pd["ttft_agg_ms"] = round(float(pred[0]), 3)
        return pd

    def _pd_decide(self, req, dec_ep, pre_ep, uncached: int) -> dict:
        """Split iff predicted TTFT on P + hop beats aggregated prefill on D.

        Without predictor stamps (no predicted-latency-producer in the config)
        the decider degrades to the legacy threshold-only behavior: past the
        uncached-suffix threshold, always split.
        """
        preds = req.state.get(STATE_PREDICTED) or {}
        dec_pred = preds.get(dec_ep.address)
        pre_pred = preds.get(pre_ep.address)
        hop_ms = self._pd_hop_ms(uncached)
        pd = {"uncached_tokens": uncached, "hop_ms": round(hop_ms, 3),
              "prefill": pre_ep.address, "decode": dec_ep.address}
        if dec_pred is None or pre_pred is None:
            pd.update(decision="split", reason="no_predictor")
            return pd
        ttft_agg = float(dec_pred[0])  # prefill runs on D, no hop
        ttft_split = float(pre_pred[0]) + hop_ms  # prefill on P, then pull
        split = ttft_split + self.pd_margin_ms < ttft_agg
        pd.update(
            decision="split" if split else "aggregated",
            reason="predicted_ttft" if split else "hop_not_worth_it",
            ttft_agg_ms=round(ttft_agg, 3),
            ttft_split_ms=round(ttft_split, 3),
            delta_ms=round(ttft_agg - ttft_split, 3))
        return pd
