"""Gateway-mode EPP: the Envoy external-processing (ext_proc) gRPC front.

In gateway mode the reference's EPP does not proxy traffic itself — an Envoy
(or any GAIE-conformant gateway) parks each request and consults the EPP over
the ext_proc bidirectional stream; the EPP answers with header mutations naming
the chosen pod (``x-gateway-destination-endpoint``) and Envoy forwards
(/root/reference/docs/architecture/core/router/proxy.md:3-111,
docs/architecture/core/router/epp/README.md:13-16). This module is that server
for the TPU stack: it reuses the standalone RouterServer's scheduling plane
(parser → flow control → producers → scheduler) and speaks the ext_proc wire
protocol via the checked-in clean-room proto subset
(protos/ext_proc.proto, wire-compatible field numbers), registered under
Envoy's full method name so a real Envoy can front it.

Phase handling (buffered / FULL_DUPLEX-style chunked bodies both work):
- request_headers → captured; CONTINUE.
- request_body chunks → buffered; non-final chunks CONTINUE; the final chunk
  triggers the pick and its BodyResponse carries the routing header mutation
  (+ body mutation when InferenceModelRewrite rewrote the model name).
- flow-control rejection / no endpoint → ImmediateResponse with the
  flow-control outcome's HTTP status — unless the InferencePool's
  ``failureMode`` is FailOpen, in which case CONTINUE without a mutation lets
  the gateway fall back to its default routing
  (docs/api-reference/inferencepool.md failureMode semantics).
- response_headers/response_body → observed for usage/latency feedback
  (scheduler.post_response drives the inflight/latency producers); CONTINUE.
"""

from __future__ import annotations

import asyncio
import json
import time
from concurrent import futures
from typing import Iterator, Optional

import grpc

from llmd_tpu.router import ext_proc_pb2 as pb
from llmd_tpu.router.server import RouterServer

# Envoy's service/method name — what an ext_proc filter dials.
ENVOY_SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"
HDR_DESTINATION = "x-gateway-destination-endpoint"
# Standard gRPC health protocol — Envoy's ext_proc cluster preset health-checks
# the EPP with grpc_health_check (guides/no-kubernetes-deployment router
# envoy.yaml in the reference); without this service a real Envoy marks the
# EPP unhealthy and never opens a stream.
HEALTH_SERVICE = "grpc.health.v1.Health"
# grpc.health.v1.HealthCheckResponse { ServingStatus status = 1; } SERVING=1 —
# hand-encoded (field 1, varint wire type, value 1); the 2-field health proto
# doesn't warrant a generated module.
_HEALTH_SERVING = b"\x08\x01"


def _headers_to_dict(hm: pb.HeaderMap) -> dict[str, str]:
    out: dict[str, str] = {}
    for h in hm.headers:
        v = h.value or (h.raw_value.decode("utf-8", "replace") if h.raw_value else "")
        out[h.key.lower()] = v
    return out


def _mutation(headers: dict[str, str]) -> pb.HeaderMutation:
    return pb.HeaderMutation(set_headers=[
        pb.HeaderValueOption(
            header=pb.HeaderValue(key=k, raw_value=v.encode()),
            append_action=2,  # OVERWRITE_IF_EXISTS_OR_ADD
        )
        for k, v in headers.items()
    ])


def _continue_headers() -> pb.ProcessingResponse:
    return pb.ProcessingResponse(request_headers=pb.HeadersResponse(
        response=pb.CommonResponse(status=pb.CommonResponse.CONTINUE)))


class _Stream:
    """Per-request state across the phases of one ext_proc stream."""

    RESP_BUFFER_CAP = 256 * 1024  # usage parse only needs the (small) JSON body

    def __init__(self) -> None:
        self.headers: dict[str, str] = {}
        self.path = "/v1/completions"
        self.body = bytearray()
        self.resp_body = bytearray()
        self.resp_streaming = False  # SSE bodies carry no parseable usage JSON
        self.req = None
        self.endpoint = None
        self.t_start = time.monotonic()
        self.resp_status = 0


class ExtProcEPP:
    """ext_proc gRPC server over an existing RouterServer's scheduling plane."""

    def __init__(self, router: RouterServer, host: str = "0.0.0.0", port: int = 0,
                 failure_mode: str = "FailClose", max_streams: int = 256) -> None:
        self.router = router
        self.host, self.port = host, port
        self.failure_mode = failure_mode
        # one worker thread is pinned per ext_proc stream for the stream's whole
        # HTTP lifetime (sync gRPC server); max_streams bounds concurrency and
        # excess streams are REJECTED (RESOURCE_EXHAUSTED) rather than queued
        # behind long LLM responses
        self.max_streams = max_streams
        self._server: Optional[grpc.Server] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        import threading

        self._stopping = threading.Event()  # releases parked Watch streams
        self.metrics = {"streams_total": 0, "picks_total": 0,
                        "immediate_total": 0, "fail_open_total": 0}

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Must be awaited from the router's event loop (flow control and async
        producers are loop-bound; grpc worker threads bounce through it)."""
        self._loop = asyncio.get_running_loop()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=self.max_streams, thread_name_prefix="extproc"),
            maximum_concurrent_rpcs=self.max_streams,
        )
        rpc = grpc.stream_stream_rpc_method_handler(
            self._process,
            request_deserializer=pb.ProcessingRequest.FromString,
            response_serializer=pb.ProcessingResponse.SerializeToString,
        )
        health_check = grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: _HEALTH_SERVING,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )

        def _watch(req, ctx):
            # the health protocol requires Watch to STAY OPEN and stream
            # status changes — a completed stream reads as a failure to
            # Watch-based checkers. One SERVING now, then hold until the
            # server stops (our status never changes while serving).
            yield _HEALTH_SERVING
            while ctx.is_active() and not self._stopping.wait(timeout=1.0):
                pass

        health_watch = grpc.unary_stream_rpc_method_handler(
            _watch,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(ENVOY_SERVICE, {"Process": rpc}),
            grpc.method_handlers_generic_handler(
                HEALTH_SERVICE, {"Check": health_check, "Watch": health_watch}),
        ))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        if self.prometheus_lines not in self.router.extra_metrics:
            self.router.extra_metrics.append(self.prometheus_lines)

    async def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            self._server.stop(grace=1.0)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- helpers -----------------------------------------------------------
    def _await(self, coro, timeout: float = 600.0):
        """Run a coroutine on the router loop from a grpc worker thread."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(timeout)

    def _immediate(self, status: int, message: str) -> pb.ProcessingResponse:
        self.metrics["immediate_total"] += 1
        body = json.dumps({"error": {"message": message}}).encode()
        return pb.ProcessingResponse(immediate_response=pb.ImmediateResponse(
            status=pb.HttpStatus(code=status), body=body, details=message))

    @staticmethod
    def _wrap(phase: str, common: pb.CommonResponse) -> pb.ProcessingResponse:
        """Envoy requires the response oneof to match the request phase."""
        if phase == "request_headers":
            return pb.ProcessingResponse(
                request_headers=pb.HeadersResponse(response=common))
        return pb.ProcessingResponse(request_body=pb.BodyResponse(response=common))

    def _fail(self, st: _Stream, phase: str, status: int, message: str,
              deliberate: bool = False) -> pb.ProcessingResponse:
        """Reject, honouring the pool's failureMode for EPP-side failures only.

        failureMode governs what happens when the EPP *can't* answer
        (inferencepool.md) — deliberate admission decisions (flow-control
        shedding, priority rejection) are always enforced, or FailOpen would
        disable load shedding exactly under the saturation it exists for."""
        if self.failure_mode == "FailOpen" and not deliberate:
            self.metrics["fail_open_total"] += 1
            return self._wrap(phase, pb.CommonResponse(
                status=pb.CommonResponse.CONTINUE))
        return self._immediate(status, message)

    # -- the pick ----------------------------------------------------------
    def _pick(self, st: _Stream, phase: str = "request_body") -> pb.ProcessingResponse:
        r = self.router
        try:
            body = json.loads(bytes(st.body) or b"{}")
        except json.JSONDecodeError:
            return self._immediate(400, "invalid JSON body")
        rewritten = dict(body)
        req = r.prepare_request(st.path, rewritten, st.headers)
        st.req = req
        # one admission semantics with the standalone HTTP front
        try:
            result, err = self._await(r.admit_and_schedule(req))
        except Exception as e:  # EPP-internal failure → failureMode applies
            return self._fail(st, phase, 500, f"EPP error: {e}")
        if err is not None:
            return self._fail(st, phase, err.status, err.message,
                              deliberate=err.deliberate)
        st.endpoint = result.endpoint
        self.metrics["picks_total"] += 1

        from llmd_tpu.core.request import HDR_PREFILLER_HOST_PORT

        hdrs = {
            HDR_DESTINATION: result.endpoint.address,
            "x-llm-d-endpoint": result.endpoint.address,
            "x-llm-d-request-id": req.request_id,
        }
        if result.prefill_endpoint is not None:
            hdrs[HDR_PREFILLER_HOST_PORT] = result.prefill_endpoint.address
        common = pb.CommonResponse(
            status=pb.CommonResponse.CONTINUE,
            header_mutation=_mutation(hdrs),
            clear_route_cache=True,
        )
        if rewritten.get("model") != body.get("model") and phase == "request_body":
            # plain CONTINUE + body_mutation: CONTINUE_AND_REPLACE would stop
            # Envoy sending the response phases, blinding usage/latency feedback
            # for exactly the canary traffic the rewrite exists to measure
            common.body_mutation.body = json.dumps(rewritten).encode()
        return self._wrap(phase, common)

    def _finish(self, st: _Stream) -> None:
        """Feed the response back to the latency/inflight producers — on the
        router loop: producers' post_response mutates shared per-endpoint state
        and the HTTP path posts from the loop, so gRPC worker threads must not
        call it directly."""
        if st.req is None or st.endpoint is None:
            return
        info = {"status": st.resp_status,
                "e2e_ms": (time.monotonic() - st.t_start) * 1e3}
        try:
            usage = json.loads(bytes(st.resp_body)).get("usage", {})
            info["usage"] = usage
            if usage.get("completion_tokens"):
                info["itl_ms"] = info["e2e_ms"] / usage["completion_tokens"]
        except Exception:
            pass
        req, ep = st.req, st.endpoint
        st.req = None  # post once
        try:
            self._loop.call_soon_threadsafe(
                self.router.scheduler.post_response, req, ep, info)
        except RuntimeError:
            pass  # loop shut down mid-stream

    # -- stream handler ----------------------------------------------------
    def _process(self, request_iterator: Iterator[pb.ProcessingRequest],
                 context) -> Iterator[pb.ProcessingResponse]:
        self.metrics["streams_total"] += 1
        st = _Stream()
        try:
            for msg in request_iterator:
                which = msg.WhichOneof("request")
                if which == "request_headers":
                    st.headers = _headers_to_dict(msg.request_headers.headers)
                    st.path = st.headers.get(":path", st.path)
                    if msg.request_headers.end_of_stream:
                        # no body (GET-ish) — pick on headers alone
                        yield self._pick(st, phase="request_headers")
                    else:
                        yield _continue_headers()
                elif which == "request_body":
                    st.body.extend(msg.request_body.body)
                    if msg.request_body.end_of_stream:
                        yield self._pick(st)
                    else:
                        yield pb.ProcessingResponse(request_body=pb.BodyResponse(
                            response=pb.CommonResponse(
                                status=pb.CommonResponse.CONTINUE)))
                elif which == "response_headers":
                    rh = _headers_to_dict(msg.response_headers.headers)
                    st.resp_status = int(rh.get(":status", "0") or 0)
                    st.resp_streaming = rh.get("content-type", "").startswith(
                        "text/event-stream")
                    if msg.response_headers.end_of_stream:
                        self._finish(st)
                    yield pb.ProcessingResponse(response_headers=pb.HeadersResponse(
                        response=pb.CommonResponse(
                            status=pb.CommonResponse.CONTINUE)))
                elif which == "response_body":
                    if (not st.resp_streaming
                            and len(st.resp_body) < _Stream.RESP_BUFFER_CAP):
                        st.resp_body.extend(msg.response_body.body)
                    if msg.response_body.end_of_stream:
                        self._finish(st)
                    yield pb.ProcessingResponse(response_body=pb.BodyResponse(
                        response=pb.CommonResponse(
                            status=pb.CommonResponse.CONTINUE)))
                elif which == "request_trailers":
                    yield pb.ProcessingResponse(
                        request_trailers=pb.TrailersResponse())
                elif which == "response_trailers":
                    self._finish(st)
                    yield pb.ProcessingResponse(
                        response_trailers=pb.TrailersResponse())
        finally:
            self._finish(st)

    def prometheus_lines(self) -> list[str]:
        m = self.metrics
        return [
            f"llm_d_epp_extproc_streams_total {m['streams_total']}",
            f"llm_d_epp_extproc_picks_total {m['picks_total']}",
            f"llm_d_epp_extproc_immediate_total {m['immediate_total']}",
            f"llm_d_epp_extproc_fail_open_total {m['fail_open_total']}",
        ]
