"""Scorer plugins (reference epp/scheduling.md:85-102) + approx prefix-cache producer.

All scorers return normalized scores in [0, 1] per endpoint (higher = better), combined
by weighted sum in the scheduler.
"""

from __future__ import annotations

import hashlib
import random
import time
from collections import OrderedDict
from typing import Any, Optional

from llmd_tpu.core.endpoint import Endpoint
from llmd_tpu.core.kv_events import block_keys_for_tokens
from llmd_tpu.core.metrics_contract import StdMetric
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.router.plugins import DataProducer, register_plugin

STATE_TOKEN_IDS = "token_ids"  # set by token-producer (render+tokenize once)
STATE_BLOCK_KEYS = "block_keys"
STATE_PREFIX_HITS = "prefix_hits"  # endpoint.address → matched tokens
STATE_PREDICTED = "predicted_latency"


def _normalize_inverse(values: dict[Endpoint, float]) -> dict[Endpoint, float]:
    """Map raw 'lower is better' values to [0,1] where lowest → 1."""
    if not values:
        return {}
    mx = max(values.values())
    if mx <= 0:
        return {e: 1.0 for e in values}
    return {e: 1.0 - v / mx for e, v in values.items()}


def clamp_scores(scores: dict[Endpoint, float],
                 within: dict[Endpoint, Any]) -> dict[Endpoint, float]:
    """Clamp a scorer result to the post-filter candidate set ``within``.

    Scorers are handed the surviving candidates, but one working off cached
    state (a stale snapshot taken before a filter pass) can hand back scores
    for endpoints that were filtered out. Entries outside the candidate set
    are dropped, and if the dropped entry held the normalization max the
    survivors are rescaled so the best of them is 1.0 again — otherwise a
    stale scorer's effective weight silently shrinks relative to its peers
    in the weighted sum. Well-behaved scorers pass through untouched."""
    if all(e in within for e in scores):
        return scores
    kept = {e: s for e, s in scores.items() if e in within}
    mx = max(kept.values(), default=0.0)
    if 0.0 < mx < 1.0:
        inv = 1.0 / mx
        kept = {e: s * inv for e, s in kept.items()}
    return kept


@register_plugin("queue-depth-scorer")
class QueueDepthScorer:
    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        return _normalize_inverse({e: e.metric(StdMetric.QUEUED_REQUESTS) for e in endpoints})


@register_plugin("kv-cache-utilization-scorer")
class KVCacheUtilizationScorer:
    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        return {e: 1.0 - min(1.0, e.metric(StdMetric.KV_UTILIZATION)) for e in endpoints}


@register_plugin("running-requests-scorer")
class RunningRequestsScorer:
    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        return _normalize_inverse({e: e.metric(StdMetric.RUNNING_REQUESTS) for e in endpoints})


@register_plugin("token-load-scorer")
class TokenLoadScorer:
    """Approximate per-endpoint in-flight token load (scheduling.md token-load)."""

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any]) -> None:
        self.inflight = ctx.setdefault("inflight_tokens", {})

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        return _normalize_inverse({e: float(self.inflight.get(e.address, 0)) for e in endpoints})


@register_plugin("session-affinity-scorer")
class SessionAffinityScorer:
    """Stable-hash the fairness/session id onto endpoints (scheduling.md session-affinity)."""

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        sid = req.fairness_id or req.request_id
        if not endpoints:
            return {}
        h = int(hashlib.md5(sid.encode()).hexdigest()[:8], 16)
        chosen = sorted(endpoints, key=lambda e: e.address)[h % len(endpoints)]
        return {e: (1.0 if e == chosen else 0.0) for e in endpoints}


@register_plugin("lora-affinity-scorer")
class LoraAffinityScorer:
    """Prefer endpoints already serving the requested adapter (model-servers.md:55-75)."""

    def __init__(self, loaded_weight: float = 1.0, waiting_weight: float = 0.6,
                 free_weight: float = 0.3) -> None:
        self.loaded_weight, self.waiting_weight, self.free_weight = (
            loaded_weight, waiting_weight, free_weight)

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        adapter = req.lora_adapter or req.model
        out: dict[Endpoint, float] = {}
        for e in endpoints:
            info = e.attrs.get(StdMetric.LORA_INFO) or {}
            running = info.get("running", [])
            waiting = info.get("waiting", [])
            max_lora = info.get("max_lora", 0)
            if adapter in running:
                out[e] = self.loaded_weight
            elif adapter in waiting:
                out[e] = self.waiting_weight
            elif max_lora and len(running) < max_lora:
                out[e] = self.free_weight
            else:
                out[e] = 0.0
        return out


class _LRUSet:
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._d: OrderedDict[Any, float] = OrderedDict()

    def add(self, key: Any) -> None:
        self._d[key] = time.monotonic()
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def __contains__(self, key: Any) -> bool:
        return key in self._d


@register_plugin("approx-prefix-cache-producer")
class ApproxPrefixCacheProducer(DataProducer):
    """Router-side model of each endpoint's prefix cache (no KV events needed).

    Parity: reference kv-management/prefix-cache-aware-routing.md:14-60 — hash prompt
    blocks, remember which endpoint served which block chain (LRU per endpoint), score
    by longest consecutive match. The precise variant (event-driven) lives in
    llmd_tpu/kv/indexer.py.
    """

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any], blockSize: int = 16,
                 lruCapacityPerServer: int = 31250, maxPrefixBlocks: int = 256) -> None:
        self.block_size = blockSize
        self.capacity = lruCapacityPerServer
        self.max_blocks = maxPrefixBlocks
        self.tables: dict[str, _LRUSet] = ctx.setdefault("approx_prefix_tables", {})

    def _table(self, address: str) -> _LRUSet:
        t = self.tables.get(address)
        if t is None:
            t = self.tables[address] = _LRUSet(self.capacity)
        return t

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None:
        token_ids = req.state.get(STATE_TOKEN_IDS)
        if token_ids is None:
            token_ids = [b for b in req.prompt_text().encode("utf-8")]
            req.state[STATE_TOKEN_IDS] = token_ids
        # The approx index is router-internal, so its lora term only needs to
        # ISOLATE traffic classes: `lora_adapter or model` covers the canary flow
        # where the adapter is addressed as the model name (adapter-rollout.md) —
        # adapter traffic then builds affinity separately from base traffic.
        # (The precise producer must instead match engine-computed hashes, which
        # requires the explicit lora_adapter field.)
        keys = block_keys_for_tokens(token_ids, self.block_size,
                                     req.lora_adapter or req.model,
                                     req.mm_hashes)[: self.max_blocks]
        req.state[STATE_BLOCK_KEYS] = keys
        hits: dict[str, int] = {}
        for e in endpoints:
            t = self._table(e.address)
            n = 0
            for k in keys:
                if k in t:
                    n += 1
                else:
                    break
            hits[e.address] = n * self.block_size
        req.state[STATE_PREFIX_HITS] = hits

    def pre_request(self, req: InferenceRequest, endpoint: Endpoint) -> None:
        # speculative insert: assume the chosen endpoint now caches the whole chain
        t = self._table(endpoint.address)
        for k in req.state.get(STATE_BLOCK_KEYS, []):
            t.add(k)


@register_plugin("prefix-cache-scorer")
class PrefixCacheScorer:
    """Score = matched-prefix fraction (uses producer output; precise or approx)."""

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        hits = req.state.get(STATE_PREFIX_HITS) or {}
        n_tokens = max(1, len(req.state.get(STATE_TOKEN_IDS) or req.prompt_text().encode()))
        return {e: min(1.0, hits.get(e.address, 0) / n_tokens) for e in endpoints}


@register_plugin("no-hit-lru-scorer")
class NoHitLRUScorer:
    """When nothing has the prefix, steer to the endpoint least-recently given a
    no-hit request — spreads fresh prefixes across the pool instead of piling them on
    the current best-scored pod (reference tiered-prefix-cache values, scheduling.md).
    """

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any]) -> None:
        self.last_no_hit: dict[str, float] = ctx.setdefault("no_hit_lru", {})

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        hits = req.state.get(STATE_PREFIX_HITS) or {}
        if any(v > 0 for v in hits.values()):
            return {e: 0.0 for e in endpoints}
        raw = {e: self.last_no_hit.get(e.address, 0.0) for e in endpoints}
        return _normalize_inverse({e: v - min(raw.values()) for e, v in raw.items()})

    def note_pick(self, endpoint: Endpoint) -> None:
        self.last_no_hit[endpoint.address] = time.monotonic()


@register_plugin("inflight-load-producer")
class InflightLoadProducer(DataProducer):
    """PreRequest++ / ResponseBody-- in-flight counters (request-handling.md)."""

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any]) -> None:
        self.counts: dict[str, int] = ctx.setdefault("inflight_requests", {})
        self.tokens: dict[str, int] = ctx.setdefault("inflight_tokens", {})

    def pre_request(self, req: InferenceRequest, endpoint: Endpoint) -> None:
        self.counts[endpoint.address] = self.counts.get(endpoint.address, 0) + 1
        n = len(req.state.get(STATE_TOKEN_IDS) or []) + req.sampling.max_tokens
        self.tokens[endpoint.address] = self.tokens.get(endpoint.address, 0) + n

    def post_response(self, req: InferenceRequest, endpoint: Endpoint,
                      response_info: dict[str, Any]) -> None:
        self.counts[endpoint.address] = max(0, self.counts.get(endpoint.address, 0) - 1)
        n = len(req.state.get(STATE_TOKEN_IDS) or []) + req.sampling.max_tokens
        self.tokens[endpoint.address] = max(0, self.tokens.get(endpoint.address, 0) - n)
