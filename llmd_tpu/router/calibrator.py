"""Flow-control calibrator: band capacities from engine capacity + workload.

The reference ships its flow-control tuning math as an offline wizard
(`guides/flow-control/scripts/tuning_wizard.py:1-30` — Little's-law compute
constraint + CLT KV-memory constraint); SURVEY hard-part #5 calls for that math
to be a BUILT-IN calibrator. This module is it: given the serving fleet's KV
capacity and an observed workload (token rates, ISL/OSL moments, request
sizes), it computes the system's sustainable concurrency and sizes every
priority band's ``maxRequests`` / ``maxBytes`` / ``ttl_s`` so the queue
buffers what the fleet can actually absorb — no starvation from bands sized
too small, no unbounded memory from bands sized "just big".

The two constraints (same model as the reference wizard, same defaults):

- **Compute (Little's law)**: a fleet sustaining ``throughput`` requests/s at
  mean latency ``W`` holds ``L = throughput x W`` requests in service; queued
  work beyond that waits.
- **KV memory (CLT)**: n concurrent requests' paged-KV footprint is
  approximately ``n*mu + z*sqrt(n)*sigma`` tokens (mu/sigma the per-request
  footprint moments over an autoregressive lifetime: ISL + OSL/2, with the
  output variance of a uniformly-progressing decode). The largest n keeping
  that under the usable pool solves ``mu*s^2 + z*sigma*s - available = 0``
  for ``s = sqrt(n)``.

The queue then buffers a bounded multiple of the binding constraint, split
across bands by weight.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Optional

from llmd_tpu.core.config import FlowControlSpec, PriorityBandSpec


@dataclass
class EngineCapacity:
    """The fleet's KV pool, as engines report it (vllm:cache_config_info)."""

    num_pages: int  # total KV blocks across the pool's engines
    page_size: int = 16
    paged_attention_efficiency: float = 0.90  # fragmentation headroom
    shared_prefix_tokens: int = 0  # static system prompt covered by the cache
    enable_prefix_caching: bool = True
    max_num_batched_tokens: int = 2048


@dataclass
class WorkloadObservation:
    """Observed workload moments (engine traces / EPP metrics window)."""

    throughput_rps: float  # completed requests per second
    latency_s: float  # mean end-to-end seconds
    isl_mean: float
    osl_mean: float
    # exponential-distribution fallback matches the wizard: std = mean
    isl_std: Optional[float] = None
    osl_std: Optional[float] = None
    isl_osl_correlation: float = 0.0
    mean_request_bytes: int = 2048  # JSON body size, for maxBytes

    def __post_init__(self) -> None:
        if self.isl_std is None:
            self.isl_std = self.isl_mean
        if self.osl_std is None:
            self.osl_std = self.osl_mean


@dataclass
class Calibration:
    compute_limit: int
    memory_limit: int
    lookahead_buffer: int
    footprint_cv: float  # coefficient of variation of the KV footprint
    spec: FlowControlSpec = field(default_factory=FlowControlSpec)

    @property
    def concurrency_limit(self) -> int:
        return min(self.compute_limit, self.memory_limit)

    @property
    def binding_constraint(self) -> str:
        return "compute" if self.compute_limit <= self.memory_limit else "memory"


def compute_constraint(throughput_rps: float, latency_s: float) -> int:
    """Little's law: L = lambda x W."""
    return max(1, math.floor(throughput_rps * latency_s))


def memory_constraint(cap: EngineCapacity, wl: WorkloadObservation,
                      z_score: float = 2.0) -> tuple[int, float]:
    """Max concurrency before KV exhaustion; returns (limit, footprint CV)."""
    effective = cap.num_pages * cap.page_size * cap.paged_attention_efficiency
    if cap.enable_prefix_caching:
        available = max(0.0, effective - cap.shared_prefix_tokens)
        marginal_isl = max(0.0, wl.isl_mean - cap.shared_prefix_tokens)
    else:
        available, marginal_isl = effective, wl.isl_mean
    isl_std = wl.isl_std if marginal_isl > 0 else 0.0

    # mean KV held over a request's life: full prompt + half the output ramp
    mu = marginal_isl + wl.osl_mean / 2.0
    var_output = wl.osl_std ** 2 / 3.0 + wl.osl_mean ** 2 / 12.0
    var = isl_std ** 2 + var_output + wl.isl_osl_correlation * isl_std * wl.osl_std
    sigma = math.sqrt(max(0.0, var))
    cv = sigma / mu if mu > 0 else 0.0
    if mu <= 0:
        return 1, cv
    # n*mu + z*sqrt(n)*sigma <= available, s = sqrt(n)
    disc = (z_score * sigma) ** 2 + 4 * mu * available
    s = (-z_score * sigma + math.sqrt(disc)) / (2 * mu)
    return max(1, int(s ** 2)), cv


def lookahead_buffer(active_batch: int, max_num_batched_tokens: int,
                     isl_mean: Optional[float]) -> int:
    """Engine-local queue depth keeping continuous batching fed — capped at
    15% of the active batch (the wizard's starvation-vs-HOL compromise)."""
    cap15 = math.ceil(active_batch * 0.15)
    if not isl_mean or isl_mean <= 0:
        return max(1, cap15)
    return max(1, min(math.ceil(max_num_batched_tokens / isl_mean), cap15))


def calibrate(cap: EngineCapacity, wl: WorkloadObservation,
              bands: Optional[list[PriorityBandSpec]] = None,
              band_weights: Optional[dict[int, float]] = None,
              z_score: float = 2.0, queue_factor: float = 2.0,
              ttl_margin: float = 3.0) -> Calibration:
    """Size every band from the binding constraint.

    - total queue budget = ``queue_factor`` x concurrency limit (absorb a
      burst of that multiple before shedding — beyond it, waiting requests
      would outlive any sane deadline anyway);
    - per band: the budget splits by ``band_weights`` (default: equal);
    - ``maxBytes`` = that request budget x observed mean request size;
    - ``ttl_s`` = ``ttl_margin`` x (service latency + expected full-queue
      drain time at observed throughput): a request older than that has
      missed its window — evict instead of serving into a timeout.
    """
    comp = compute_constraint(wl.throughput_rps, wl.latency_s)
    mem, cv = memory_constraint(cap, wl, z_score=z_score)
    limit = min(comp, mem)
    bands = [replace(b) for b in (bands or [PriorityBandSpec(priority=0,
                                                             name="default")])]
    weights = {b.priority: (band_weights or {}).get(b.priority, 1.0)
               for b in bands}
    wsum = sum(weights.values()) or 1.0
    queue_budget = max(len(bands), math.ceil(limit * queue_factor))
    drain_s = (queue_budget / wl.throughput_rps
               if wl.throughput_rps > 0 else 60.0)
    ttl = ttl_margin * (wl.latency_s + drain_s)
    for b in bands:
        share = weights[b.priority] / wsum
        b.max_requests = max(1, math.ceil(queue_budget * share))
        b.max_bytes = b.max_requests * max(1, wl.mean_request_bytes)
        b.ttl_s = ttl
    return Calibration(
        compute_limit=comp, memory_limit=mem,
        lookahead_buffer=lookahead_buffer(limit, cap.max_num_batched_tokens,
                                          wl.isl_mean),
        footprint_cv=cv,
        spec=FlowControlSpec(enabled=True, bands=bands),
    )


def main() -> None:
    """CLI twin of the reference wizard (non-interactive): prints the
    calibrated flowControl YAML block for the router config."""
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--throughput", type=float, required=True, help="mean RPS")
    ap.add_argument("--latency-sec", type=float, required=True)
    ap.add_argument("--num-pages", type=int, required=True,
                    help="total KV blocks across the fleet")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--isl-mean", type=float, required=True)
    ap.add_argument("--isl-std", type=float, default=None)
    ap.add_argument("--osl-mean", type=float, required=True)
    ap.add_argument("--osl-std", type=float, default=None)
    ap.add_argument("--shared-prefix", type=int, default=0)
    ap.add_argument("--no-prefix-caching", action="store_true")
    ap.add_argument("--max-num-batched-tokens", type=int, default=2048)
    ap.add_argument("--mean-request-bytes", type=int, default=2048)
    ap.add_argument("--z-score", type=float, default=2.0)
    ap.add_argument("--queue-factor", type=float, default=2.0)
    ap.add_argument("--bands", default="0",
                    help="comma-separated priority[:weight] list, e.g. 0:1,10:3")
    args = ap.parse_args()

    bands, weights = [], {}
    for part in args.bands.split(","):
        prio, _, w = part.partition(":")
        bands.append(PriorityBandSpec(priority=int(prio), name=f"band{prio}"))
        weights[int(prio)] = float(w) if w else 1.0
    cal = calibrate(
        EngineCapacity(num_pages=args.num_pages, page_size=args.page_size,
                       shared_prefix_tokens=args.shared_prefix,
                       enable_prefix_caching=not args.no_prefix_caching,
                       max_num_batched_tokens=args.max_num_batched_tokens),
        WorkloadObservation(throughput_rps=args.throughput,
                            latency_s=args.latency_sec,
                            isl_mean=args.isl_mean, isl_std=args.isl_std,
                            osl_mean=args.osl_mean, osl_std=args.osl_std,
                            mean_request_bytes=args.mean_request_bytes),
        bands=bands, band_weights=weights,
        z_score=args.z_score, queue_factor=args.queue_factor,
    )
    print(json.dumps({
        "compute_limit": cal.compute_limit,
        "memory_limit": cal.memory_limit,
        "binding_constraint": cal.binding_constraint,
        "concurrency_limit": cal.concurrency_limit,
        "lookahead_buffer": cal.lookahead_buffer,
        "footprint_cv": round(cal.footprint_cv, 3),
        "flowControl": {
            "enabled": True,
            "bands": [{
                "priority": b.priority, "name": b.name,
                "maxRequests": b.max_requests, "maxBytes": b.max_bytes,
                "ttl_s": round(b.ttl_s, 1),
            } for b in cal.spec.bands],
        },
    }, indent=2))


if __name__ == "__main__":
    main()
