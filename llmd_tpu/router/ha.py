"""EPP high availability: leader election (active-passive) + active-active.

Reference semantics
(/root/reference/docs/architecture/core/router/epp/configuration.md:455-459;
docs/architecture/advanced/kv-management/kv-indexer.md:77-101):

- **Active-passive** — EPP replicas > 1 run leader election; only the leader
  answers picks, standbys take over when the leader's lease lapses. The k8s
  deployment uses a coordination.k8s.io Lease (``K8sLease`` here, plain HTTP
  API with resourceVersion optimistic concurrency); co-located processes (the
  no-Kubernetes mode) use an flock-held ``FileLease`` — the OS drops the lock
  on crash, so failover needs no timeout heuristics.
- **Active-active** — for precise prefix routing, leader election is DISABLED
  and every replica subscribes to all pods' KV event streams (pod-discovery
  mode); each replica's index converges on the same state, so any replica
  produces the same pick. There is no code to add for this beyond what
  pod-discovery already does — tests/test_ha.py asserts the convergence
  property across two full RouterServers.

``attach_ha`` wires an elector into a RouterServer: standby replicas answer
generate requests 503 "standby replica" (the gateway's health checks and
retries move traffic to the leader; /health reports the role) while /metrics
keeps serving. The deployment CLI enables it with ``--ha-lease-file PATH``
(co-located processes) or ``--ha-k8s-lease NAME`` (in-cluster).
"""

from __future__ import annotations

import asyncio
import calendar
import os
import time
import uuid
from typing import Callable, Optional

import aiohttp


class FileLease:
    """flock-based lease for co-located replicas: the OS releases the lock the
    instant the holder dies — crash failover without staleness heuristics."""

    def __init__(self, path: str, identity: Optional[str] = None) -> None:
        self.path = path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._fd: Optional[int] = None

    def try_acquire(self) -> bool:
        import fcntl

        if self._fd is not None:
            return True
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.pwrite(fd, self.identity.encode(), 0)
        self._fd = fd
        return True

    def renew(self) -> bool:
        return self._fd is not None  # flock holds until released/crash

    def release(self) -> None:
        if self._fd is not None:
            os.close(self._fd)  # closes → flock released
            self._fd = None

    def holder(self) -> Optional[str]:
        try:
            with open(self.path) as f:
                return f.read().strip() or None
        except OSError:
            return None


class K8sLease:
    """coordination.k8s.io/v1 Lease over the plain k8s API.

    Acquire: create (201) or take over when ``renewTime`` is older than the
    lease duration, via PUT preconditioned on resourceVersion — a 409 means a
    peer won the race. Renew: PUT our own record with a fresh renewTime.
    """

    def __init__(self, name: str, namespace: str = "default",
                 identity: Optional[str] = None, lease_seconds: float = 5.0,
                 api_base: Optional[str] = None, token: Optional[str] = None) -> None:
        from llmd_tpu.router.discovery import K8sWatchSource

        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.lease_seconds = lease_seconds
        self.api_base = api_base or K8sWatchSource._in_cluster_base()
        self.token = token if token is not None else K8sWatchSource._in_cluster_token()
        self._session: Optional[aiohttp.ClientSession] = None
        self._held = False

    @property
    def _url(self) -> str:
        return (f"{self.api_base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases/{self.name}")

    def _headers(self) -> dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _body(self, rv: Optional[str] = None) -> dict:
        meta: dict = {"name": self.name, "namespace": self.namespace}
        if rv:
            meta["resourceVersion"] = rv
        from datetime import datetime, timezone

        return {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": max(1, int(self.lease_seconds)),
                # k8s MicroTime (RFC3339 with microseconds) — whole-second
                # stamps would alias a fresh lease as up-to-1s stale
                "renewTime": datetime.now(timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%S.%fZ"),
            },
        }

    async def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def try_acquire(self) -> bool:
        s = await self._ensure_session()
        try:
            async with s.get(self._url, headers=self._headers()) as r:
                if r.status == 404:
                    base = self._url.rsplit("/", 1)[0]
                    async with s.post(base, headers=self._headers(),
                                      json=self._body()) as c:
                        self._held = c.status in (200, 201)
                        return self._held
                r.raise_for_status()
                lease = await r.json()
            spec = lease.get("spec", {})
            holder = spec.get("holderIdentity")
            renew = spec.get("renewTime", "1970-01-01T00:00:00.000000Z")
            try:
                # tolerate both MicroTime and second-precision RFC3339 ('...Z')
                whole = renew.split(".")[0].rstrip("Z")
                frac = (float("0." + renew.split(".")[1].rstrip("Z"))
                        if "." in renew else 0.0)
                age = time.time() - calendar.timegm(
                    time.strptime(whole, "%Y-%m-%dT%H:%M:%S")) - frac
            except (ValueError, IndexError):
                age = float("inf")  # unparseable renewTime = stale, takeover OK
            if holder not in (None, "", self.identity) and age < self.lease_seconds:
                self._held = False
                return False
            rv = lease.get("metadata", {}).get("resourceVersion")
            async with s.put(self._url, headers=self._headers(),
                             json=self._body(rv)) as u:
                self._held = u.status == 200  # 409: a peer won the race
                return self._held
        except aiohttp.ClientError:
            self._held = False
            return False

    async def renew(self) -> bool:
        return await self.try_acquire()

    async def release(self) -> None:
        if self._held:
            s = await self._ensure_session()
            try:
                async with s.get(self._url, headers=self._headers()) as r:
                    if r.status == 200:
                        lease = await r.json()
                        if lease.get("spec", {}).get("holderIdentity") == self.identity:
                            lease["spec"]["holderIdentity"] = ""
                            async with s.put(self._url, headers=self._headers(),
                                             json=lease):
                                pass
            except aiohttp.ClientError:
                pass
        self._held = False
        if self._session is not None and not self._session.closed:
            await self._session.close()


class LeaderElector:
    """Drives a lease on an interval; flips ``is_leader`` and notifies."""

    def __init__(self, lease, interval_s: float = 0.5,
                 on_change: Optional[Callable[[bool], None]] = None) -> None:
        self.lease = lease
        self.interval = interval_s
        self.on_change = on_change
        self.is_leader = False
        self.transitions = 0
        self._task: Optional[asyncio.Task] = None

    async def _tick(self) -> None:
        fn = self.lease.renew if self.is_leader else self.lease.try_acquire
        got = fn()
        if asyncio.iscoroutine(got):
            got = await got
        if got != self.is_leader:
            self.is_leader = got
            self.transitions += 1
            if self.on_change:
                self.on_change(got)

    async def start(self) -> None:
        await self._tick()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._tick()
            except Exception:
                if self.is_leader:
                    self.is_leader = False
                    self.transitions += 1
                    if self.on_change:
                        self.on_change(False)

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        rel = self.lease.release()
        if asyncio.iscoroutine(rel):
            await rel
        if self.is_leader:
            self.is_leader = False
            self.transitions += 1
            if self.on_change:
                self.on_change(False)


def attach_ha(router, elector: LeaderElector) -> None:
    """Gate the router's generate path on leadership (active-passive mode).

    Standby replicas answer 503 "standby replica" (gateway health checks and
    retries move traffic to the leader); /metrics, /health, /v1/models keep
    serving so the replica stays observable — /health reports the role.
    The ext-proc front shares the same gate through admit_and_schedule.
    Call BEFORE ``router.start()`` — route registration binds the handlers at
    start time.
    """
    router.elector = elector
    orig = router.admit_and_schedule

    async def gated(req, span=None):
        if not elector.is_leader:
            from llmd_tpu.router.server import Rejection

            # deliberate: a FailOpen gateway must not bypass the leader gate
            return None, Rejection(503, "standby replica (leader election)",
                                   deliberate=True)
        return await orig(req, span=span)

    router.admit_and_schedule = gated

    async def health(request):
        from aiohttp import web

        return web.json_response({
            "status": "ok", "endpoints": len(router.pool),
            "role": "leader" if elector.is_leader else "standby",
        })

    router._health = health
    router.extra_metrics.append(lambda: [
        f"llm_d_epp_leader {1 if elector.is_leader else 0}",
        f"llm_d_epp_leader_transitions_total {elector.transitions}",
    ])
