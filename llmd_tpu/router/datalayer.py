"""Data layer: Source→Extract→Attribute runtime feeding Endpoint.attrs.

Parity: reference epp/datalayer.md:5-91 — PollingDataSource scraping each endpoint's
/metrics (core-metrics-extractor mapping engine names → standard keys), plus the
file-discovery endpoint source for no-Kubernetes mode
(guides/no-kubernetes-deployment/router/epp/config.yaml:10-41). A k8s watch source
slots in behind the same EndpointPool interface.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

import aiohttp

from llmd_tpu.core.endpoint import Endpoint, EndpointPool, EndpointRole
from llmd_tpu.core.metrics_contract import map_engine_metrics, parse_prometheus


class Extractor:
    """Polling-source extractor (datalayer.md 'Extractor' interface): transform
    one endpoint's raw source payload into attributes on that endpoint."""

    name = "extractor"

    def extract(self, ep: Endpoint, raw) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class CoreMetricsExtractor(Extractor):
    """core-metrics-extractor: engine-specific metric names → the standard
    attribute keys scorers consume (kv_usage, waiting, running, ...), with
    per-engine mapping so multiple inference engines coexist in one pool."""

    name = "core-metrics-extractor"

    def extract(self, ep: Endpoint, raw: list) -> None:
        for k, v in map_engine_metrics(ep.engine_type, raw).items():
            ep.attrs.put(k, v)


class EndpointExtractor:
    """Endpoint-lifecycle extractor (datalayer.md endpoint-notification-source
    consumer): set up / tear down per-endpoint state as the pool changes."""

    name = "endpoint-extractor"

    def on_endpoint_added(self, ep: Endpoint) -> None:  # pragma: no cover
        pass

    def on_endpoint_removed(self, ep: Endpoint) -> None:  # pragma: no cover
        pass


class DataLayerRuntime:
    """Source→extractor mapping + endpoint-event dispatch (datalayer.md
    'Runtime'). Polling sources register their extractor chains here; endpoint
    extractors bind to the pool's add/remove events."""

    def __init__(self, pool: EndpointPool) -> None:
        self.pool = pool
        self.endpoint_extractors: list[EndpointExtractor] = []
        self.error_counts: dict[str, int] = {}  # "<extractor>:<event>" → count
        pool.subscribe(self._on_pool_event)

    def register_endpoint_extractor(self, ext: EndpointExtractor) -> None:
        self.endpoint_extractors.append(ext)
        for ep in self.pool.list():  # late registration sees existing members
            self._dispatch(ext, "added", ep)

    def _dispatch(self, ext: EndpointExtractor, kind: str, ep: Endpoint) -> None:
        try:
            if kind == "added":
                ext.on_endpoint_added(ep)
            elif kind == "removed":
                ext.on_endpoint_removed(ep)
        except Exception:
            # one extractor's failure never starves the others, but it stays
            # VISIBLE — a silently-broken lifecycle extractor is undebuggable
            key = f"{ext.name}:{kind}"
            self.error_counts[key] = self.error_counts.get(key, 0) + 1

    def _on_pool_event(self, kind: str, ep: Endpoint) -> None:
        for ext in self.endpoint_extractors:
            self._dispatch(ext, kind, ep)


class MetricsPoller:
    """metrics-data-source + its extractor chain (HOT POLL).

    Polls every pool endpoint's Prometheus endpoint on an interval and hands
    the parsed samples to the registered extractors (CoreMetricsExtractor by
    default; register more via ``extractors`` for derived attributes)."""

    def __init__(self, pool: EndpointPool, interval_s: float = 0.5,
                 timeout_s: float = 2.0, metrics_path: str = "/metrics",
                 extractors: Optional[list[Extractor]] = None) -> None:
        self.pool = pool
        self.interval = interval_s
        self.timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.metrics_path = metrics_path
        self.extractors: list[Extractor] = (
            list(extractors) if extractors is not None else [CoreMetricsExtractor()])
        self._task: Optional[asyncio.Task] = None
        self.poll_count = 0
        self.error_counts: dict[str, int] = {}
        # scrape transport failures only (llm_d_epp_scrape_errors_total feeds
        # off this; extractor bugs stay in error_counts and don't inflate it)
        self.scrape_error_count = 0
        # resilience hook: called with the endpoint address on each scrape
        # failure — the breaker's passive-health signal (router attaches it)
        self.on_scrape_error = None

    def forget(self, address: str) -> None:
        """Drop an endpoint's error-count keys when it leaves discovery —
        scale-cycle churn must not grow the map without bound. Cascades to
        extractors holding per-endpoint state (fleet rollup) for the same
        reason."""
        self.error_counts.pop(address, None)
        for key in [k for k in self.error_counts
                    if k.startswith(address + ":")]:
            del self.error_counts[key]
        for ext in self.extractors:
            fn = getattr(ext, "forget", None)
            if fn is not None:
                try:
                    fn(address)
                except Exception:
                    key = f"{address}:{ext.name}"
                    self.error_counts[key] = self.error_counts.get(key, 0) + 1

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def poll_once(self, session: aiohttp.ClientSession) -> None:
        async def one(ep: Endpoint) -> None:
            try:
                async with session.get(
                    f"http://{ep.address}{self.metrics_path}", timeout=self.timeout
                ) as resp:
                    text = await resp.text()
                raw = parse_prometheus(text)
                all_ok = True
                for ext in self.extractors:
                    try:
                        ext.extract(ep, raw)
                    except Exception:
                        # a broken extractor never starves the rest, but the
                        # failure stays VISIBLE: error counted, freshness stamp
                        # withheld so staleness-aware consumers can react
                        all_ok = False
                        key = f"{ep.address}:{ext.name}"
                        self.error_counts[key] = self.error_counts.get(key, 0) + 1
                if all_ok:
                    ep.mark_scrape_ok()
            except Exception:
                # Scrape transport failure: the last-known metrics would
                # otherwise look fresh forever — flag the endpoint stale so
                # consumers (breaker passive health, /v1/models aggregation)
                # stop trusting it, and surface the failure as a counter.
                self.error_counts[ep.address] = self.error_counts.get(ep.address, 0) + 1
                self.scrape_error_count += 1
                ep.mark_scrape_failed()
                if self.on_scrape_error is not None:
                    try:
                        self.on_scrape_error(ep.address)
                    except Exception:
                        pass  # the hook must never kill the poll loop

        await asyncio.gather(*(one(e) for e in self.pool.list()))
        self.poll_count += 1

    async def _loop(self) -> None:
        async with aiohttp.ClientSession() as session:
            while True:
                await self.poll_once(session)
                await asyncio.sleep(self.interval)


def load_endpoints_file(pool: EndpointPool, path: str) -> None:
    """file-discovery: static endpoint list (JSON or line format 'addr[,role[,k=v...]]')."""
    with open(path) as f:
        text = f.read()
    try:
        entries = json.loads(text)
    except json.JSONDecodeError:
        entries = []
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(",")
            entry = {"address": parts[0]}
            if len(parts) > 1:
                entry["role"] = parts[1]
            entry["labels"] = dict(p.split("=", 1) for p in parts[2:] if "=" in p)
            entries.append(entry)
    for e in entries:
        pool.upsert(Endpoint(
            address=e["address"],
            role=EndpointRole(e.get("role", "both")),
            labels=e.get("labels", {}),
            engine_type=e.get("engineType", "vllm"),
        ))


def add_static_endpoints(pool: EndpointPool, addresses: list[str],
                         role: str = "both") -> None:
    for a in addresses:
        pool.upsert(Endpoint(address=a, role=EndpointRole(role)))
