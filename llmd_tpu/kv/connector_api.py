"""Out-of-tree KV-cache connector API (K5): third-party cache engines plug in.

The reference integrates LMCache / Mooncake / NVIDIA KVBM through the model
server's KV-cache connector API — the external engine owns indexing, memory
management, tiering and storage; the server only asks "how much of this prompt
do you hold?" and moves bytes (kv-offloader.md:8,70-100). This module is that
seam for the TPU engine, shaped for XLA's functional cache:

- scheduler-side: ``get_num_matched_blocks`` consults the external engine at
  admission, AFTER local HBM prefix hits and the native CPU/FS tiers — the
  connector covers the remaining suffix only;
- worker-side: ``load_blocks`` returns a NEW cache value (functional update —
  the engine's cache is an XLA array, not mutable memory) and ``save_blocks``
  receives block-major host bytes it may hand to any store;
- lifecycle: ``request_finished`` releases per-request resources.

Connectors register by name (``register_kv_connector``) and activate via
``EngineConfig.kv_connector`` — the out-of-tree package just imports and
registers before engine construction, no in-tree changes (the vLLM
``--kv-transfer-config`` pattern, TPU-side).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


class KVConnectorBase:
    """Interface an external KV-cache engine implements."""

    def __init__(self, params: Optional[dict] = None) -> None:
        self.params = params or {}

    # ---------------------------------------------------------- scheduler side
    def get_num_matched_blocks(self, block_hashes: list[int]) -> int:
        """How many CONSECUTIVE blocks (from the start of the given suffix
        chain) the external engine can supply. Called under the engine lock at
        admission; must be cheap (index lookup, no IO)."""
        raise NotImplementedError

    # ------------------------------------------------------------- worker side
    def load_blocks(self, cache, block_hashes: list[int], page_ids: list[int],
                    pages_per_layer: int):
        """Write the engine-layout block data for ``block_hashes`` into the
        given fresh pages. Returns (new_cache, n_loaded); n_loaded < requested
        means the tail was unavailable after all (engine recomputes it)."""
        raise NotImplementedError

    def save_blocks(self, block_hashes: list[int], token_chunks: list[list[int]],
                    blocks: "np.ndarray") -> None:
        """Persist block-major host bytes ([n, L, ps, 2Hk, Dhp]) keyed by the
        chained hashes. Called off the engine hot loop (retirement path)."""
        raise NotImplementedError

    # -------------------------------------------------------------- lifecycle
    def request_finished(self, request_id: str) -> None:  # pragma: no cover
        pass


_REGISTRY: dict[str, Callable[[Optional[dict]], KVConnectorBase]] = {}


def register_kv_connector(name: str,
                          factory: Callable[[Optional[dict]], KVConnectorBase]) -> None:
    _REGISTRY[name] = factory


# in-tree connectors register at module import; map names to their modules so
# an EngineConfig naming one works without the caller importing it first
_BUILTIN_MODULES = {"remote-store": "llmd_tpu.kv.remote_store"}


def build_kv_connector(name: str, params: Optional[dict] = None) -> KVConnectorBase:
    if name not in _REGISTRY and name in _BUILTIN_MODULES:
        import importlib

        importlib.import_module(_BUILTIN_MODULES[name])
    if name not in _REGISTRY:
        raise KeyError(f"unknown KV connector {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](params)


class InMemoryKVConnector(KVConnectorBase):
    """Reference connector: a process-local dict store (what LMCache would be
    with its engine replaced by a dict). Ships in-tree as the worked example
    and CI-testable stand-in for external engines."""

    def __init__(self, params: Optional[dict] = None) -> None:
        super().__init__(params)
        self.store: dict[int, np.ndarray] = {}
        self.stats = {"saved_blocks": 0, "loaded_blocks": 0, "lookups": 0}

    def get_num_matched_blocks(self, block_hashes: list[int]) -> int:
        self.stats["lookups"] += 1
        n = 0
        for h in block_hashes:
            if h not in self.store:
                break
            n += 1
        return n

    def load_blocks(self, cache, block_hashes, page_ids, pages_per_layer):
        from llmd_tpu.disagg.transfer import insert_blocks

        # CONSECUTIVE prefix only: the engine commits returned blocks under
        # block_hashes[:n_loaded] positionally — skipping a missing middle
        # block would commit wrong bytes under the wrong hash and silently
        # poison the prefix cache for every future sharer
        have: list[int] = []
        for h in block_hashes[: len(page_ids)]:
            if h not in self.store:
                break
            have.append(h)
        if not have:
            return cache, 0
        blocks = np.stack([self.store[h] for h in have])
        cache = insert_blocks(cache, page_ids[: len(have)], blocks, pages_per_layer)
        self.stats["loaded_blocks"] += len(have)
        return cache, len(have)

    def save_blocks(self, block_hashes, token_chunks, blocks) -> None:
        for h, b in zip(block_hashes, blocks):
            self.store[h] = np.array(b)
        self.stats["saved_blocks"] += len(block_hashes)


register_kv_connector("in-memory", InMemoryKVConnector)
