"""Alternative KV-index backends behind one interface (K1 backends table,
reference docs/architecture/advanced/kv-management/kv-indexer.md:64-101).

The Index is the router's hot data structure — every scoring call queries it,
every KV event updates it — and the reference offers three backends for it:

- **in-memory** (default): the two-level LRU ``KVBlockIndex`` (kv/indexer.py),
  entry-count bounded — predictable sizing, lowest latency;
- **cost-aware**: byte-budget bounded with admission control (the Ristretto
  role) — for workloads whose per-entry size varies (many pods per block,
  multimodal/LoRA metadata). ``CostAwareKVBlockIndex`` below: LRU eviction by
  estimated bytes plus a doorkeeper that lets a brand-new key in only on its
  second sighting while the index is under pressure, so one-shot scans can't
  flush the working set;
- **external** (Redis/Valkey wire): the index lives in an external RESP server
  shared by every EPP replica — strong cross-replica consistency at a network
  hop per lookup. ``ExternalKVBlockIndex`` speaks a minimal pipelined RESP
  client (no driver dependency); any Redis-protocol store works
  (llmd_tpu.testing.resp_server is the in-repo fixture). Memory policy is the
  store's own (maxmemory-lru), not ours.

``build_index`` selects by name — the precise-prefix producer and RouterServer
take ``indexBackend``/``indexParams`` from plugin/kvEvents config.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional, Sequence

from llmd_tpu.core.kv_events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    KVEvent,
    MEDIUM_HBM,
)
from llmd_tpu.kv.indexer import (
    DEFAULT_TIER_WEIGHTS,
    IndexStats,
    KVBlockIndex,
    PrefixMatch,
)

# ---------------------------------------------------------------------------
# Cost-aware backend
# ---------------------------------------------------------------------------

# rough CPython heap costs: dict slot + int key + OrderedDict node overhead
KEY_COST_BYTES = 120
POD_ENTRY_COST_BYTES = 160


class CostAwareKVBlockIndex(KVBlockIndex):
    """Byte-budget LRU with doorkeeper admission (the Ristretto role)."""

    def __init__(self, max_bytes: int = 64 << 20,
                 doorkeeper_size: int = 4096, **kw) -> None:
        kw.setdefault("max_keys", 1 << 62)  # bytes, not entry count, bound us
        super().__init__(**kw)
        self.max_bytes = max_bytes
        self._doorkeeper: set[int] = set()
        self._doorkeeper_size = doorkeeper_size
        self._pod_entries = 0  # total (block, pod) pairs, kept incrementally

    # account (block, pod) pair lifecycle — every removal path funnels
    # through _drop in the base class
    def _drop(self, pod: str, block_hash: int) -> None:
        self._pod_entries -= 1
        super()._drop(pod, block_hash)

    def estimated_bytes(self) -> int:
        with self._lock:  # reentrant: _store calls this with the lock held
            return (len(self._index) * KEY_COST_BYTES
                    + self._pod_entries * POD_ENTRY_COST_BYTES)

    def _store(self, pod: str, block_hash: int, tier: str,
               spec_expiry: float) -> None:
        is_new_key = block_hash not in self._index
        if is_new_key and self.estimated_bytes() >= self.max_bytes:
            # under pressure a never-seen key must knock twice: one-shot scans
            # (a crawler, a mass warmup) otherwise flush the hot working set
            if block_hash not in self._doorkeeper:
                if len(self._doorkeeper) >= self._doorkeeper_size:
                    self._doorkeeper.clear()
                self._doorkeeper.add(block_hash)
                return
            self._doorkeeper.discard(block_hash)
        pods_before = self._index.get(block_hash)
        had_pod = pods_before is not None and pod in pods_before
        super()._store(pod, block_hash, tier, spec_expiry)
        if not had_pod and pod in self._index.get(block_hash, {}):
            self._pod_entries += 1
        while (self.estimated_bytes() > self.max_bytes and len(self._index) > 1):
            evicted_hash, evicted_pods = self._index.popitem(last=False)
            for p in evicted_pods:
                self._drop(p, evicted_hash)
            self.stats.evictions += 1


# ---------------------------------------------------------------------------
# External (Redis/Valkey wire) backend
# ---------------------------------------------------------------------------


def _resp_encode(*parts: bytes) -> bytes:
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        out.append(b"$%d\r\n%s\r\n" % (len(p), p))
    return b"".join(out)


class _RespClient:
    """Minimal pipelined RESP2 client (SET-free subset the index needs)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0) -> None:
        self.host, self.port, self.timeout_s = host, port, timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()

    def _connect(self) -> None:
        # llmd-lint: allow[lock-blocking-call] the lock serialises whole RESP round trips over one socket; connect is timeout-bounded and only ever runs under it
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self.timeout_s)
        self._buf = b""

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            # llmd-lint: allow[lock-blocking-call] reply reads are part of the locked round trip; socket timeout bounds the wait
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("RESP peer closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            # llmd-lint: allow[lock-blocking-call] reply reads are part of the locked round trip; socket timeout bounds the wait
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("RESP peer closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RuntimeError(f"RESP error: {rest.decode()}")
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if t == b"*":
            n = int(rest)
            return None if n == -1 else [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"bad RESP type {line!r}")

    def pipeline(self, commands: Sequence[Sequence[bytes]]) -> list:
        """Send all commands in one write, read all replies — the index's
        multi-block operations are one round trip each."""
        if not commands:
            return []
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                # llmd-lint: allow[lock-blocking-call] pipelining contract: one writer sends the whole batch and drains every reply before the lock is released
                self._sock.sendall(b"".join(_resp_encode(*c) for c in commands))
                return [self._read_reply() for _ in commands]
            except (OSError, ConnectionError):
                self._sock = None
                raise

    def cmd(self, *parts: bytes):
        return self.pipeline([parts])[0]

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


def _enc_tiers(tiers: dict[str, float]) -> bytes:
    return ",".join(f"{t}:{e}" for t, e in tiers.items()).encode()


def _dec_tiers(raw: bytes) -> dict[str, float]:
    out: dict[str, float] = {}
    for part in raw.decode().split(","):
        if part:
            t, _, e = part.partition(":")
            out[t] = float(e)
    return out


class ExternalKVBlockIndex:
    """KVBlockIndex semantics over a Redis/Valkey-wire store.

    Layout: hash ``kv:<block>`` maps pod → "tier:expiry,..." (0 = confirmed by
    an engine event, else absolute time.time() expiry of a speculative entry —
    wall clock, not monotonic: entries are read by OTHER replicas/processes);
    set ``kvpod:<pod>`` tracks the pod's blocks for clears/removal; hash
    ``kvlora`` holds learned adapter generation keys. Failures degrade to
    "no external hits" — serving never depends on the store answering.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 tier_weights: Optional[dict[str, float]] = None,
                 speculative_ttl_s: float = 2.0, timeout_s: float = 5.0,
                 max_keys: Optional[int] = None,
                 max_pods_per_key: Optional[int] = None) -> None:
        # max_keys / max_pods_per_key accepted for config uniformity but the
        # STORE owns its memory policy (maxmemory-lru on a real Valkey)
        del max_keys, max_pods_per_key
        self.client = _RespClient(host, port, timeout_s)
        self.tier_weights = dict(tier_weights or DEFAULT_TIER_WEIGHTS)
        self.spec_ttl = speculative_ttl_s
        self._lora_cache: dict[str, str] = {}
        self.stats = IndexStats()

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _key(h: int) -> bytes:
        return b"kv:%d" % h

    def _merge_tier(self, h: int, pod: str, tier: str, expiry: float) -> None:
        key, p = self._key(h), pod.encode()
        raw = self.client.cmd(b"HGET", key, p)
        tiers = _dec_tiers(raw) if raw else {}
        cur = tiers.get(tier)
        if expiry == 0.0 or cur is None or cur != 0.0:
            tiers[tier] = expiry
        self.client.pipeline([
            (b"HSET", key, p, _enc_tiers(tiers)),
            (b"SADD", b"kvpod:" + p, b"%d" % h),
        ])

    # -- events ------------------------------------------------------------
    def apply(self, pod: str, event: KVEvent) -> None:
        try:
            self._apply(pod, event)
            self.stats.events_applied += 1
        except (OSError, ConnectionError, RuntimeError):
            pass  # store outage: the index degrades to no-hits

    def _apply(self, pod: str, event: KVEvent) -> None:
        if isinstance(event, BlockStored):
            if event.lora_id and "@" in event.lora_id:
                name = event.lora_id.split("@", 1)[0]
                self._lora_cache[name] = event.lora_id
                self.client.cmd(b"HSET", b"kvlora", name.encode(),
                                event.lora_id.encode())
            for h in event.block_hashes:
                self._merge_tier(h, pod, event.medium, 0.0)
            self.stats.blocks_stored += len(event.block_hashes)
        elif isinstance(event, BlockRemoved):
            p = pod.encode()
            for h in event.block_hashes:
                key = self._key(h)
                raw = self.client.cmd(b"HGET", key, p)
                if raw is None:
                    continue
                tiers = _dec_tiers(raw)
                tiers.pop(event.medium, None)
                if tiers:
                    self.client.cmd(b"HSET", key, p, _enc_tiers(tiers))
                else:
                    self.client.pipeline([
                        (b"HDEL", key, p),
                        (b"SREM", b"kvpod:" + p, b"%d" % h),
                    ])
            self.stats.blocks_removed += len(event.block_hashes)
        elif isinstance(event, AllBlocksCleared):
            self.remove_pod(pod)
            self.stats.clears += 1

    def apply_batch(self, pod: str, events: Sequence[KVEvent]) -> None:
        for ev in events:
            self.apply(pod, ev)

    # -- speculative -------------------------------------------------------
    def add_speculative(self, pod: str, block_hashes: Sequence[int],
                        tier: str = MEDIUM_HBM) -> None:
        expiry = time.time() + self.spec_ttl
        try:
            for h in block_hashes:
                self._merge_tier(h, pod, tier, expiry)
            self.stats.speculative_inserts += len(block_hashes)
        except (OSError, ConnectionError, RuntimeError):
            pass

    # -- lookup ------------------------------------------------------------
    def lookup(self, block_hashes: Sequence[int],
               pods: Sequence[str]) -> dict[str, PrefixMatch]:
        out = {p: PrefixMatch() for p in pods}
        self.stats.lookups += 1
        if not block_hashes:
            return out
        try:
            replies = self.client.pipeline(
                [(b"HGETALL", self._key(h)) for h in block_hashes])
        except (OSError, ConnectionError, RuntimeError):
            return out
        now = time.time()
        live = set(pods)
        for reply in replies:
            if not live or not reply:
                break
            entry = {reply[i].decode(): _dec_tiers(reply[i + 1])
                     for i in range(0, len(reply), 2)}
            matched_any = False
            for p in list(live):
                tiers = entry.get(p)
                live_tiers = [t for t, e in (tiers or {}).items()
                              if e == 0.0 or now < e]
                if not live_tiers:
                    live.discard(p)
                    continue
                m = out[p]
                m.blocks += 1
                m.weighted += max(self.tier_weights.get(t, 0.0)
                                  for t in live_tiers)
                matched_any = True
            if not matched_any:
                break
        return out

    def pods_for_block(self, block_hash: int) -> dict[str, list[str]]:
        now = time.time()
        try:
            reply = self.client.cmd(b"HGETALL", self._key(block_hash)) or []
        except (OSError, ConnectionError, RuntimeError):
            return {}
        out = {}
        for i in range(0, len(reply), 2):
            tiers = _dec_tiers(reply[i + 1])
            live = [t for t, e in tiers.items() if e == 0.0 or now < e]
            if live:
                out[reply[i].decode()] = live
        return out

    # -- lifecycle ---------------------------------------------------------
    def resolve_lora_key(self, name: Optional[str]) -> Optional[str]:
        if not name:
            return name
        if name in self._lora_cache:
            return self._lora_cache[name]
        try:
            raw = self.client.cmd(b"HGET", b"kvlora", name.encode())
        except (OSError, ConnectionError, RuntimeError):
            return name
        if raw:
            self._lora_cache[name] = raw.decode()
            return self._lora_cache[name]
        return name

    def remove_pod(self, pod: str) -> None:
        p = pod.encode()
        try:
            members = self.client.cmd(b"SMEMBERS", b"kvpod:" + p) or []
            if members:
                self.client.pipeline(
                    [(b"HDEL", b"kv:" + m, p) for m in members]
                    + [(b"DEL", b"kvpod:" + p)])
            else:
                self.client.cmd(b"DEL", b"kvpod:" + p)
        except (OSError, ConnectionError, RuntimeError):
            pass

    def __len__(self) -> int:
        try:
            return int(self.client.cmd(b"DBSIZE"))
        except (OSError, ConnectionError, RuntimeError):
            return 0


# ---------------------------------------------------------------------------

BACKENDS = {
    "in-memory": KVBlockIndex,
    "cost-aware": CostAwareKVBlockIndex,
    "external": ExternalKVBlockIndex,
}


def build_index(backend: str = "in-memory", **params):
    """Index factory for config selection (kvEvents.indexBackend /
    precise-prefix producer ``indexBackend``)."""
    try:
        cls = BACKENDS[backend]
    except KeyError:
        raise KeyError(
            f"unknown index backend {backend!r}; known: {sorted(BACKENDS)}")
    return cls(**params)
