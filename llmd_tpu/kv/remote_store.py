"""Remote KV store over DCN: the InfiniStore/remote-LMCache role (N9/K5).

A standalone content-addressed block store any engine reaches over TCP, behind
the out-of-tree connector seam (kv/connector_api.py) — KV computed by one pod
survives pod restarts and feeds OTHER pods' admissions, the cross-pod tier the
reference gets from InfiniStore-backed LMCache (Dockerfile.cuda:55-59,
kv-offloader.md:70-100). Design choices for this stack:

- content-addressed by chained block hash (the same keys the prefix cache and
  KV events use), so admission can ask for a consecutive chain directly;
- framed wire protocol in the house style (MAGIC + JSON header + raw payload,
  like disagg/transfer.py) — one long-lived store, many short-lived clients;
- byte-budget LRU eviction server-side (external stores manage their own
  capacity — the engine never has to care, matching the FS-backend contract).

Wire protocol (request → response):
  MAGIC ‖ u32 len ‖ JSON header ‖ payload?
  ops: put   {hashes, dtype, shape, nbytes} + payload   → {stored}
       get   {hashes}                  → {found, dtype, shape, nbytes} + payload
       probe {hashes}                  → {found}         (consecutive prefix)
       stats {}                        → counters
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

from llmd_tpu.kv.connector_api import KVConnectorBase, register_kv_connector

MAGIC = b"KVS1"


@dataclass
class StoreFaults:
    """Fault injection for the KVS1 server, in the testing/fake_server.py
    FaultConfig idiom — chaos tests drive real wire frames, not mocks."""

    error_rate: float = 0.0          # fraction of ops answered {"error": ...}
    connect_refuse: bool = False     # accept then close before the request
    latency_s: float = 0.0           # per-op service delay
    first_byte_delay_s: float = 0.0  # delay before the get response frame
    corrupt_payload: bool = False    # flip one byte per block (after crc)
    hangup_rate: float = 0.0         # fraction of gets cut mid-payload
    seed: int = 0


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _send_frame(conn: socket.socket, header: dict, payload: bytes = b"") -> None:
    hdr = json.dumps(header).encode()
    conn.sendall(MAGIC + struct.pack("<I", len(hdr)) + hdr + payload)


def _recv_frame(conn: socket.socket) -> tuple[dict, "socket.socket"]:
    if _recv_exact(conn, 4) != MAGIC:
        raise ConnectionError("bad magic")
    (hlen,) = struct.unpack("<I", _recv_exact(conn, 4))
    return json.loads(_recv_exact(conn, hlen)), conn


def resolve_dtype(name: str) -> np.dtype:
    """np.dtype(name), extended to accelerator dtypes.

    'bfloat16' / 'float8_*' only resolve after ml_dtypes registers them with
    numpy. Engine processes get that for free via jax, but the standalone
    store server never imports jax — without the lazy import here a bf16
    engine's every put would bounce with "bad put header dtype".
    """
    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes  # noqa: F401  (import registers the names)
        except ImportError as e:
            raise TypeError(f"data type {name!r} not understood") from e
        return np.dtype(name)  # still a TypeError for genuine garbage


def verify_crc_prefix(body: bytes, n: int, crcs) -> int:
    """Longest verified consecutive block prefix of a get payload.

    Truncating at the first checksum mismatch (rather than rejecting the
    whole payload) keeps the consecutive-prefix property admission relies
    on: everything before the corrupt block is still committable. A store
    predating the crc header (no list) passes through unverified.
    """
    if not crcs or n <= 0:
        return max(0, n)
    per = len(body) // n
    for i in range(min(n, len(crcs))):
        if zlib.crc32(body[i * per : (i + 1) * per]) != int(crcs[i]):
            return i
    return n


class RemoteKVStoreServer:
    """Content-addressed block store with a byte-budget LRU."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_bytes: int = 1 << 30) -> None:
        self.host, self.port = host, port
        self.max_bytes = max_bytes
        # guarded-by: _lock — entries are (blob, dtype, shape, crc32)
        self._blocks: OrderedDict[int, tuple[bytes, str, tuple, int]] = (
            OrderedDict())
        self._bytes = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()
        # guarded-by: _lock
        self.stats = {"puts": 0, "gets": 0, "probes": 0, "evictions": 0,
                      "hit_blocks": 0, "miss_blocks": 0}
        self.faults = StoreFaults()
        self._fault_rng = random.Random(self.faults.seed)
        # guarded-by: _lock
        self.fault_counts = {"refused": 0, "errors": 0, "hangups": 0,
                             "corrupted": 0}

    def set_faults(self, **kw) -> None:
        for k, v in kw.items():
            if not hasattr(self.faults, k):
                raise AttributeError(f"unknown fault knob {k!r}")
            setattr(self.faults, k, v)
        self._fault_rng = random.Random(self.faults.seed)

    def start(self) -> None:
        self._srv = socket.create_server((self.host, self.port))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="kv-store").start()

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            # close() alone does NOT interrupt a thread blocked in accept()
            # on Linux — the syscall pins the kernel socket, which keeps
            # accepting (and serving) connections until accept returns. A
            # self-connection wakes it so the loop observes _stop and exits.
            wake_host = ("127.0.0.1" if self.host in ("0.0.0.0", "::")
                         else self.host)
            try:
                with socket.create_connection((wake_host, self.port),
                                              timeout=0.2):
                    pass
            except OSError:
                pass
            self._srv.close()

    # -- storage -----------------------------------------------------------
    def _put(self, hashes: list[int], dtype: str, shape: tuple,
             payload: bytes) -> int:
        # a truncated/misaligned client frame must not be stored under content
        # hashes that later read back as valid KV bytes: nbytes must be exactly
        # n blocks of the declared dtype/shape
        try:
            expect = (len(hashes) * int(np.prod(shape or (1,)))
                      * resolve_dtype(dtype).itemsize)
        except (TypeError, ValueError) as e:  # np.dtype('bogus') is a TypeError
            raise ValueError(f"bad put header dtype/shape: {e}") from e
        if len(payload) != expect:
            raise ValueError(
                f"put payload {len(payload)}B != {len(hashes)} blocks of "
                f"{dtype}{tuple(shape)} = {expect}B")
        per = len(payload) // max(1, len(hashes))
        with self._lock:
            for i, h in enumerate(hashes):
                if h in self._blocks:
                    self._blocks.move_to_end(h)
                    continue
                blob = payload[i * per : (i + 1) * per]
                # crc captured at ingest: a get response carries it so clients
                # can reject payloads corrupted on the wire (or by fault
                # injection) without trusting the transport
                self._blocks[h] = (blob, dtype, tuple(shape), zlib.crc32(blob))
                self._bytes += len(blob)
            while self._bytes > self.max_bytes and self._blocks:
                _h, (blob, _d, _s, _c) = self._blocks.popitem(last=False)
                self._bytes -= len(blob)
                self.stats["evictions"] += 1
            self.stats["puts"] += 1
        return len(hashes)

    def _prefix(self, hashes: list[int], touch: bool) -> list[int]:
        """Consecutive found prefix (the only shape admission can commit)."""
        out = []
        with self._lock:
            for h in hashes:
                if h not in self._blocks:
                    break
                if touch:
                    self._blocks.move_to_end(h)
                out.append(h)
        return out

    def _get(self, hashes: list[int]) -> tuple[
            list[int], list[tuple[bytes, str, tuple, int]]]:
        """Consecutive prefix AND its blobs under ONE critical section.

        Scanning the prefix and fetching the blobs under separate lock
        acquisitions is a poison race: a concurrent put-triggered eviction can
        remove a middle block between the two, and the client would commit a
        non-consecutive payload positionally under the consecutive hash chain.
        """
        have: list[int] = []
        blobs: list[tuple[bytes, str, tuple, int]] = []
        with self._lock:
            for h in hashes:
                entry = self._blocks.get(h)
                if entry is None:
                    break
                self._blocks.move_to_end(h)
                have.append(h)
                blobs.append(entry)
        return have, blobs

    # -- server loop -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        f = self.faults
        try:
            with conn:
                if f.connect_refuse:
                    # accept-then-slam: the client's next read raises
                    # ConnectionError, same failure class as a refused connect
                    with self._lock:
                        self.fault_counts["refused"] += 1
                    return
                if f.latency_s:
                    time.sleep(f.latency_s)
                hdr, _ = _recv_frame(conn)
                op = hdr.get("op")
                if f.error_rate and self._fault_rng.random() < f.error_rate:
                    if op == "put":  # drain the payload so the socket is clean
                        _recv_exact(conn, int(hdr.get("nbytes", 0)))
                    with self._lock:
                        self.fault_counts["errors"] += 1
                    _send_frame(conn, {"error": "injected fault",
                                       "stored": 0, "found": 0})
                    return
                if op == "put":
                    payload = _recv_exact(conn, int(hdr["nbytes"]))
                    try:
                        n = self._put([int(h) for h in hdr["hashes"]],
                                      hdr["dtype"], hdr["shape"], payload)
                    except ValueError as e:
                        _send_frame(conn, {"error": str(e), "stored": 0})
                    else:
                        _send_frame(conn, {"stored": n})
                elif op == "probe":
                    hashes = [int(h) for h in hdr["hashes"]]
                    have = self._prefix(hashes, touch=False)
                    with self._lock:
                        self.stats["hit_blocks"] += len(have)
                        self.stats["miss_blocks"] += len(hashes) - len(have)
                        self.stats["probes"] += 1
                    _send_frame(conn, {"found": len(have)})
                elif op == "get":
                    hashes = [int(h) for h in hdr["hashes"]]
                    have, blobs = self._get(hashes)
                    with self._lock:
                        self.stats["hit_blocks"] += len(have)
                        self.stats["miss_blocks"] += len(hashes) - len(have)
                        self.stats["gets"] += 1
                    payload = b"".join(b for b, _d, _s, _c in blobs)
                    meta = blobs[0] if blobs else (b"", "float32", (), 0)
                    resp = {"found": len(blobs),
                            "dtype": meta[1],
                            "shape": list(meta[2]),
                            "crc": [c for _b, _d, _s, c in blobs],
                            "nbytes": len(payload)}
                    if f.first_byte_delay_s:
                        time.sleep(f.first_byte_delay_s)
                    if f.corrupt_payload and payload:
                        # flip a byte per block AFTER the crc list was built:
                        # the client's checksum verify is what must catch it
                        per = len(payload) // max(1, len(blobs))
                        buf = bytearray(payload)
                        for i in range(len(blobs)):
                            buf[i * per] ^= 0xFF
                        payload = bytes(buf)
                        with self._lock:
                            self.fault_counts["corrupted"] += 1
                    if (payload and f.hangup_rate
                            and self._fault_rng.random() < f.hangup_rate):
                        hdrb = json.dumps(resp).encode()
                        conn.sendall(MAGIC + struct.pack("<I", len(hdrb))
                                     + hdrb + payload[: len(payload) // 2])
                        with self._lock:
                            self.fault_counts["hangups"] += 1
                        return  # with-block slams the socket mid-frame
                    _send_frame(conn, resp, payload)
                elif op == "stats":
                    with self._lock:
                        _send_frame(conn, {**self.stats,
                                           "blocks": len(self._blocks),
                                           "bytes": self._bytes})
                else:
                    _send_frame(conn, {"error": f"unknown op {op!r}"})
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass  # client vanished mid-op: next client gets a fresh thread


class RemoteKVConnector(KVConnectorBase):
    """Engine-side connector speaking the store protocol (registered as
    ``remote-store``; EngineConfig.kv_connector_params = {host, port})."""

    def __init__(self, params: Optional[dict] = None) -> None:
        super().__init__(params)
        p = self.params
        self.host = p.get("host", "127.0.0.1")
        self.port = int(p.get("port", 0))
        self.timeout = float(p.get("timeout_s", 5.0))
        # get_num_matched_blocks runs under the engine scheduling lock — the
        # connector API's own contract says 'must be cheap (index lookup, no
        # IO)', so the admission probe gets a far tighter deadline than the
        # bulk get/put paths: a blackholed store must not stall the step loop
        self.probe_timeout = float(p.get("probe_timeout_s", 0.25))
        # circuit breakers: after `breaker_errors` CONSECUTIVE failures the
        # path goes dark for `breaker_cooldown_s` rather than paying a timeout
        # per call forever. TWO independent breakers: the admission probe's
        # tight deadline must not conflate a slow-but-healthy store (probe
        # times out at 0.25s, bulk get/put fine within 5s) with a dead one —
        # probe failures only stop probing; bulk failures stop everything.
        self.breaker_errors = int(p.get("breaker_errors", 3))
        self.breaker_cooldown = float(p.get("breaker_cooldown_s", 30.0))
        self._consec_errors = {"probe": 0, "bulk": 0}
        self._open_until = {"probe": 0.0, "bulk": 0.0}
        self.stats = {"errors": 0, "breaker_trips": 0, "breaker_skips": 0}

    def _rpc(self, header: dict, payload: bytes = b"",
             timeout: Optional[float] = None) -> tuple[dict, bytes]:
        with socket.create_connection((self.host, self.port),
                                      timeout=timeout or self.timeout) as conn:
            _send_frame(conn, header, payload)
            resp, _ = _recv_frame(conn)
            body = _recv_exact(conn, int(resp["nbytes"])) if resp.get("nbytes") else b""
            return resp, body

    def _breaker_open(self, path: str) -> bool:
        import time as _time

        now = _time.monotonic()
        # a bulk-path outage silences the probe too (probing a dead store from
        # under the engine lock is the stall the breaker exists to prevent)
        for key in ({"probe", "bulk"} if path == "probe" else {path}):
            if (self._consec_errors[key] >= self.breaker_errors
                    and now < self._open_until[key]):
                self.stats["breaker_skips"] += 1
                return True
        return False

    def _record(self, ok: bool, path: str = "bulk") -> None:
        import time as _time

        if ok:
            self._consec_errors[path] = 0
            if path == "bulk":
                # bulk success proves the store alive: give the probe its
                # trial back immediately instead of waiting out the cooldown
                self._open_until["probe"] = 0.0
            return
        self.stats["errors"] += 1
        self._consec_errors[path] += 1
        if self._consec_errors[path] == self.breaker_errors:
            self.stats["breaker_trips"] += 1
        if self._consec_errors[path] >= self.breaker_errors:
            self._open_until[path] = _time.monotonic() + self.breaker_cooldown

    def get_num_matched_blocks(self, block_hashes: list[int]) -> int:
        if self._breaker_open("probe"):
            return 0
        try:
            resp, _ = self._rpc({"op": "probe", "hashes": block_hashes},
                                timeout=self.probe_timeout)
            self._record(ok=True, path="probe")
            return int(resp.get("found", 0))
        except (OSError, ConnectionError, KeyError, ValueError):
            self._record(ok=False, path="probe")
            return 0  # store down/slow = no external hits; serving continues

    def load_blocks(self, cache, block_hashes, page_ids, pages_per_layer):
        from llmd_tpu.disagg.transfer import insert_blocks

        want = block_hashes[: len(page_ids)]
        if self._breaker_open("bulk"):
            return cache, 0
        try:
            resp, body = self._rpc({"op": "get", "hashes": want})
            n = int(resp.get("found", 0))
            if n == 0:
                self._record(ok=True)
                return cache, 0
            n = verify_crc_prefix(body, n, resp.get("crc"))
            # a corrupt payload is a store-path failure (repeats should trip
            # the breaker), but the verified consecutive prefix is still good
            self._record(ok=n == int(resp["found"]))
            if n == 0:
                return cache, 0
            per = len(body) // int(resp["found"])
            blocks = np.frombuffer(body[: n * per],
                                   dtype=resolve_dtype(resp["dtype"])).reshape(
                (n, *resp["shape"]))
            cache = insert_blocks(cache, page_ids[:n], blocks, pages_per_layer)
            return cache, n
        except (OSError, ConnectionError, KeyError, ValueError):
            self._record(ok=False)
            return cache, 0

    def save_blocks(self, block_hashes, token_chunks, blocks) -> None:
        if self._breaker_open("bulk"):
            return
        arr = np.ascontiguousarray(blocks)
        try:
            self._rpc({"op": "put", "hashes": list(block_hashes),
                       "dtype": str(arr.dtype), "shape": list(arr.shape[1:]),
                       "nbytes": arr.nbytes}, arr.tobytes())
            self._record(ok=True)
        except (OSError, ConnectionError):
            self._record(ok=False)  # best-effort tier


register_kv_connector("remote-store", RemoteKVConnector)


def main() -> None:
    """CLI: python -m llmd_tpu.kv.remote_store --port 9400 --max-gb 8"""
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9400)
    ap.add_argument("--max-gb", type=float, default=8.0)
    args = ap.parse_args()
    srv = RemoteKVStoreServer(args.host, args.port,
                              max_bytes=int(args.max_gb * (1 << 30)))
    srv.start()
    print(f"llmd-tpu remote KV store on {srv.host}:{srv.port} "
          f"({args.max_gb} GB budget)", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
