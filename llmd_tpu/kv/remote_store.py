"""Remote KV store over DCN: the InfiniStore/remote-LMCache role (N9/K5).

A standalone content-addressed block store any engine reaches over TCP, behind
the out-of-tree connector seam (kv/connector_api.py) — KV computed by one pod
survives pod restarts and feeds OTHER pods' admissions, the cross-pod tier the
reference gets from InfiniStore-backed LMCache (Dockerfile.cuda:55-59,
kv-offloader.md:70-100). Design choices for this stack:

- content-addressed by chained block hash (the same keys the prefix cache and
  KV events use), so admission can ask for a consecutive chain directly;
- framed wire protocol in the house style (MAGIC + JSON header + raw payload,
  like disagg/transfer.py) — one long-lived store, many short-lived clients;
- byte-budget LRU eviction server-side (external stores manage their own
  capacity — the engine never has to care, matching the FS-backend contract).

Wire protocol (request → response):
  MAGIC ‖ u32 len ‖ JSON header ‖ payload?
  ops: put   {hashes, dtype, shape, nbytes} + payload   → {stored}
       get   {hashes}                  → {found, dtype, shape, nbytes} + payload
       probe {hashes}                  → {found}         (consecutive prefix)
       stats {}                        → counters
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from llmd_tpu.kv.connector_api import KVConnectorBase, register_kv_connector

MAGIC = b"KVS1"


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _send_frame(conn: socket.socket, header: dict, payload: bytes = b"") -> None:
    hdr = json.dumps(header).encode()
    conn.sendall(MAGIC + struct.pack("<I", len(hdr)) + hdr + payload)


def _recv_frame(conn: socket.socket) -> tuple[dict, "socket.socket"]:
    if _recv_exact(conn, 4) != MAGIC:
        raise ConnectionError("bad magic")
    (hlen,) = struct.unpack("<I", _recv_exact(conn, 4))
    return json.loads(_recv_exact(conn, hlen)), conn


class RemoteKVStoreServer:
    """Content-addressed block store with a byte-budget LRU."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_bytes: int = 1 << 30) -> None:
        self.host, self.port = host, port
        self.max_bytes = max_bytes
        self._blocks: OrderedDict[int, tuple[bytes, str, tuple]] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.stats = {"puts": 0, "gets": 0, "probes": 0, "evictions": 0,
                      "hit_blocks": 0, "miss_blocks": 0}

    def start(self) -> None:
        self._srv = socket.create_server((self.host, self.port))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="kv-store").start()

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            self._srv.close()

    # -- storage -----------------------------------------------------------
    def _put(self, hashes: list[int], dtype: str, shape: tuple,
             payload: bytes) -> int:
        per = len(payload) // max(1, len(hashes))
        with self._lock:
            for i, h in enumerate(hashes):
                if h in self._blocks:
                    self._blocks.move_to_end(h)
                    continue
                blob = payload[i * per : (i + 1) * per]
                self._blocks[h] = (blob, dtype, tuple(shape))
                self._bytes += len(blob)
            while self._bytes > self.max_bytes and self._blocks:
                _h, (blob, _d, _s) = self._blocks.popitem(last=False)
                self._bytes -= len(blob)
                self.stats["evictions"] += 1
            self.stats["puts"] += 1
        return len(hashes)

    def _prefix(self, hashes: list[int], touch: bool) -> list[int]:
        """Consecutive found prefix (the only shape admission can commit)."""
        out = []
        with self._lock:
            for h in hashes:
                if h not in self._blocks:
                    break
                if touch:
                    self._blocks.move_to_end(h)
                out.append(h)
        return out

    # -- server loop -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                hdr, _ = _recv_frame(conn)
                op = hdr.get("op")
                if op == "put":
                    payload = _recv_exact(conn, int(hdr["nbytes"]))
                    n = self._put([int(h) for h in hdr["hashes"]],
                                  hdr["dtype"], hdr["shape"], payload)
                    _send_frame(conn, {"stored": n})
                elif op in ("get", "probe"):
                    hashes = [int(h) for h in hdr["hashes"]]
                    have = self._prefix(hashes, touch=(op == "get"))
                    self.stats["hit_blocks"] += len(have)
                    self.stats["miss_blocks"] += len(hashes) - len(have)
                    if op == "probe":
                        self.stats["probes"] += 1
                        _send_frame(conn, {"found": len(have)})
                    else:
                        self.stats["gets"] += 1
                        with self._lock:
                            blobs = [self._blocks[h] for h in have
                                     if h in self._blocks]
                        payload = b"".join(b for b, _d, _s in blobs)
                        meta = blobs[0] if blobs else (b"", "float32", ())
                        _send_frame(conn, {"found": len(blobs),
                                           "dtype": meta[1],
                                           "shape": list(meta[2]),
                                           "nbytes": len(payload)}, payload)
                elif op == "stats":
                    with self._lock:
                        _send_frame(conn, {**self.stats,
                                           "blocks": len(self._blocks),
                                           "bytes": self._bytes})
                else:
                    _send_frame(conn, {"error": f"unknown op {op!r}"})
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass  # client vanished mid-op: next client gets a fresh thread


class RemoteKVConnector(KVConnectorBase):
    """Engine-side connector speaking the store protocol (registered as
    ``remote-store``; EngineConfig.kv_connector_params = {host, port})."""

    def __init__(self, params: Optional[dict] = None) -> None:
        super().__init__(params)
        p = self.params
        self.host = p.get("host", "127.0.0.1")
        self.port = int(p.get("port", 0))
        self.timeout = float(p.get("timeout_s", 5.0))
        self.stats = {"errors": 0}

    def _rpc(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        with socket.create_connection((self.host, self.port),
                                      timeout=self.timeout) as conn:
            _send_frame(conn, header, payload)
            resp, _ = _recv_frame(conn)
            body = _recv_exact(conn, int(resp["nbytes"])) if resp.get("nbytes") else b""
            return resp, body

    def get_num_matched_blocks(self, block_hashes: list[int]) -> int:
        try:
            resp, _ = self._rpc({"op": "probe", "hashes": block_hashes})
            return int(resp.get("found", 0))
        except (OSError, ConnectionError, KeyError, ValueError):
            self.stats["errors"] += 1
            return 0  # store down = no external hits; serving continues

    def load_blocks(self, cache, block_hashes, page_ids, pages_per_layer):
        from llmd_tpu.disagg.transfer import insert_blocks

        want = block_hashes[: len(page_ids)]
        try:
            resp, body = self._rpc({"op": "get", "hashes": want})
            n = int(resp.get("found", 0))
            if n == 0:
                return cache, 0
            blocks = np.frombuffer(body, dtype=resp["dtype"]).reshape(
                (n, *resp["shape"]))
            cache = insert_blocks(cache, page_ids[:n], blocks, pages_per_layer)
            return cache, n
        except (OSError, ConnectionError, KeyError, ValueError):
            self.stats["errors"] += 1
            return cache, 0

    def save_blocks(self, block_hashes, token_chunks, blocks) -> None:
        arr = np.ascontiguousarray(blocks)
        try:
            self._rpc({"op": "put", "hashes": list(block_hashes),
                       "dtype": str(arr.dtype), "shape": list(arr.shape[1:]),
                       "nbytes": arr.nbytes}, arr.tobytes())
        except (OSError, ConnectionError):
            self.stats["errors"] += 1  # best-effort tier


register_kv_connector("remote-store", RemoteKVConnector)


def main() -> None:
    """CLI: python -m llmd_tpu.kv.remote_store --port 9400 --max-gb 8"""
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9400)
    ap.add_argument("--max-gb", type=float, default=8.0)
    args = ap.parse_args()
    srv = RemoteKVStoreServer(args.host, args.port,
                              max_bytes=int(args.max_gb * (1 << 30)))
    srv.start()
    print(f"llmd-tpu remote KV store on {srv.host}:{srv.port} "
          f"({args.max_gb} GB budget)", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
