"""Router plugins for precise (event-driven) prefix-cache routing.

Parity: reference kv-management/prefix-cache-aware-routing.md:61-100 and
kv-indexer.md:104-143 — the precise path tokenizes the prompt ONCE via the model
server's render endpoint (token-producer, kv-indexer.md:104-113), computes chained
block keys with the SAME block size as the engine (blockSize must match the engine's
``--block-size``, precise-prefix-cache-routing values), walks the event-fed
KVBlockIndex per candidate pod, and speculatively indexes the chosen pod's keys.
"""

from __future__ import annotations

from typing import Any, Optional

import aiohttp

from llmd_tpu.core.endpoint import Endpoint
from llmd_tpu.core.kv_events import block_keys_for_tokens
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.kv.indexer import KVBlockIndex
from llmd_tpu.router.plugins import DataProducer, register_plugin
from llmd_tpu.router.scorers import STATE_BLOCK_KEYS, STATE_PREFIX_HITS, STATE_TOKEN_IDS

CTX_KV_INDEX = "kv_index"
STATE_PREFIX_WEIGHTED = "prefix_weighted"  # endpoint.address → tier-weighted block sum


@register_plugin("token-producer")
class TokenProducer(DataProducer):
    """Tokenize the prompt once via a model server's render endpoint.

    The router server awaits ``aproduce`` before scheduling (the scheduler itself is
    synchronous); ``produce`` falls back to deterministic byte-level tokens when no
    render call happened (e.g. no endpoints yet) so downstream block hashing always
    has input — that matches the fake fixture's tokenizer and keeps approx routing
    self-consistent even without real tokenization.
    """

    def __init__(self, renderTimeout: float = 0.5) -> None:
        self.timeout = aiohttp.ClientTimeout(total=renderTimeout)
        self.render_calls = 0
        self.render_errors = 0
        self._last_good: Optional[str] = None  # avoid re-paying a dead endpoint's timeout
        self._cooldown_until = 0.0  # negative cache: all endpoints failed recently

    async def aproduce(self, req: InferenceRequest, endpoints: list[Endpoint],
                       session: aiohttp.ClientSession) -> None:
        if req.token_ids is not None:
            req.state[STATE_TOKEN_IDS] = list(req.token_ids)
            return
        if STATE_TOKEN_IDS in req.state:
            return
        path = "/v1/chat/completions/render" if req.messages is not None else "/v1/completions/render"
        body: dict[str, Any] = {"model": req.model}
        if req.messages is not None:
            body["messages"] = req.messages
        else:
            body["prompt"] = req.prompt or ""
        import time

        if time.monotonic() < self._cooldown_until:
            return  # every endpoint failed recently; fall back to byte-level tokens
        ordered = sorted(endpoints, key=lambda e: e.address != self._last_good)
        for ep in ordered:
            try:
                async with session.post(
                    f"http://{ep.address}{path}", json=body, timeout=self.timeout
                ) as resp:
                    data = await resp.json()
                ids = data.get("prompt_token_ids")
                if ids is not None:
                    req.state[STATE_TOKEN_IDS] = [int(t) for t in ids]
                    self.render_calls += 1
                    self._last_good = ep.address
                    return
            except Exception:
                self.render_errors += 1
                if ep.address == self._last_good:
                    self._last_good = None
                continue
        if endpoints:
            self._cooldown_until = time.monotonic() + 2.0

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None:
        if STATE_TOKEN_IDS not in req.state:
            req.state[STATE_TOKEN_IDS] = list(req.prompt_text().encode("utf-8"))


@register_plugin("precise-prefix-cache-producer")
class PrecisePrefixCacheProducer(DataProducer):
    """Walk the event-fed KV index per endpoint; speculatively index the pick."""

    needs_ctx = True

    def __init__(self, ctx: dict[str, Any], blockSize: int = 16,
                 maxPrefixBlocks: int = 1024, maxKeys: int = 1_000_000,
                 maxPodsPerKey: int = 10, speculativeTTL: float = 2.0,
                 tierWeights: Optional[dict[str, float]] = None,
                 indexBackend: str = "in-memory",
                 indexParams: Optional[dict[str, Any]] = None) -> None:
        self.block_size = blockSize
        self.max_blocks = maxPrefixBlocks
        if indexBackend == "in-memory":
            index = KVBlockIndex(
                max_keys=maxKeys, max_pods_per_key=maxPodsPerKey,
                tier_weights=tierWeights, speculative_ttl_s=speculativeTTL)
        else:
            # cost-aware / external (kv-indexer.md backends table) —
            # byte/host sizing lives in indexParams; the shared knobs
            # (maxPodsPerKey etc.) carry over rather than silently resetting
            # to backend defaults
            from llmd_tpu.kv.index_backends import build_index

            index = build_index(indexBackend, tier_weights=tierWeights,
                                speculative_ttl_s=speculativeTTL,
                                max_pods_per_key=maxPodsPerKey,
                                **(indexParams or {}))
        self.index: KVBlockIndex = ctx.setdefault(CTX_KV_INDEX, index)

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None:
        token_ids = req.state.get(STATE_TOKEN_IDS)
        if token_ids is None:
            token_ids = list(req.prompt_text().encode("utf-8"))
            req.state[STATE_TOKEN_IDS] = token_ids
        # Engines hash blocks under the generation-scoped adapter key
        # 'name@digest' (engine._lora_hash_key); hash with the index's learned
        # mapping or router-side keys never match engine-published ones.
        lora_key = self.index.resolve_lora_key(req.lora_adapter)
        keys = block_keys_for_tokens(token_ids, self.block_size, lora_key,
                                     req.mm_hashes)[: self.max_blocks]
        req.state[STATE_BLOCK_KEYS] = keys
        matches = self.index.lookup(keys, [e.address for e in endpoints])
        req.state[STATE_PREFIX_HITS] = {
            a: m.blocks * self.block_size for a, m in matches.items()
        }
        req.state[STATE_PREFIX_WEIGHTED] = {a: m.weighted for a, m in matches.items()}

    def pre_request(self, req: InferenceRequest, endpoint: Endpoint) -> None:
        keys = req.state.get(STATE_BLOCK_KEYS)
        if keys:
            self.index.add_speculative(endpoint.address, keys)


@register_plugin("precise-prefix-cache-scorer")
class PrecisePrefixCacheScorer:
    """Tier-weighted prefix score: HBM-resident prefixes beat CPU/FS-resident ones
    of the same length (kv-indexer.md tier weights gpu=1.0/cpu=0.8)."""

    def score(self, req: InferenceRequest, endpoints: list[Endpoint]) -> dict[Endpoint, float]:
        weighted = req.state.get(STATE_PREFIX_WEIGHTED)
        if weighted is None:  # fell back to approx producer: use plain hits
            hits = req.state.get(STATE_PREFIX_HITS) or {}
            n = max(1, len(req.state.get(STATE_TOKEN_IDS) or [1]))
            return {e: min(1.0, hits.get(e.address, 0) / n) for e in endpoints}
        n_blocks = max(1, len(req.state.get(STATE_BLOCK_KEYS) or [1]))
        return {e: min(1.0, weighted.get(e.address, 0.0) / n_blocks) for e in endpoints}
