"""KV-Cache Indexer: global view of which pod caches which KV block, on which tier.

Parity: reference docs/architecture/advanced/kv-management/kv-indexer.md —
- two-level LRU backend (default sized 100M keys × 10 pods; here configurable,
  kv-indexer.md:88-98),
- longest-consecutive-prefix scoring with tier weights gpu=1.0 / cpu=0.8
  (kv-indexer.md:119-143),
- speculative indexing: after the scheduler picks a pod, its prompt's block keys are
  inserted with a short TTL (default 2s) so back-to-back identical prompts route
  sticky before the engine's own events arrive (kv-indexer.md:104-143),
- event application: BlockStored / BlockRemoved / AllBlocksCleared per pod
  (kv-indexer.md:59-63).

Thread-safe: written from the ZMQ subscriber task, read on every schedule.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional, Sequence

from llmd_tpu.core.kv_events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    KVEvent,
    MEDIUM_CPU,
    MEDIUM_FS,
    MEDIUM_HBM,
)

DEFAULT_TIER_WEIGHTS = {MEDIUM_HBM: 1.0, MEDIUM_CPU: 0.8, MEDIUM_FS: 0.5}

SPECULATIVE_TTL_S = 2.0  # kv-indexer.md speculative indexing TTL


@dataclass
class _PodEntry:
    """Per-(block, pod) residency. A block can live on SEVERAL tiers of one pod at
    once (HBM evicted→CPU while still indexed, CPU demoted→FS), so tiers is a map
    tier → confirmation: 0.0 = confirmed by an engine event, else the monotonic
    expiry of a speculative entry."""

    tiers: dict[str, float] = field(default_factory=dict)

    def live(self, now: float) -> bool:
        return any(exp == 0.0 or now < exp for exp in self.tiers.values())

    def best_weight(self, weights: dict[str, float], now: float) -> float:
        live = [t for t, exp in self.tiers.items() if exp == 0.0 or now < exp]
        return max((weights.get(t, 0.0) for t in live), default=0.0)


@dataclass
class PrefixMatch:
    """Result of the longest-consecutive-prefix walk for one pod."""

    blocks: int = 0  # consecutive blocks matched from the start
    weighted: float = 0.0  # sum of tier weights over matched blocks


@dataclass
class IndexStats:
    events_applied: int = 0
    blocks_stored: int = 0
    blocks_removed: int = 0
    clears: int = 0
    lookups: int = 0
    evictions: int = 0
    speculative_inserts: int = 0


class KVBlockIndex:
    """Two-level LRU: block_hash → (pod → tier), both levels capacity-bounded."""

    def __init__(
        self,
        max_keys: int = 1_000_000,
        max_pods_per_key: int = 10,
        tier_weights: Optional[dict[str, float]] = None,
        speculative_ttl_s: float = SPECULATIVE_TTL_S,
    ) -> None:
        self.max_keys = max_keys
        self.max_pods_per_key = max_pods_per_key
        self.tier_weights = dict(tier_weights or DEFAULT_TIER_WEIGHTS)
        self.spec_ttl = speculative_ttl_s
        # adapter name → generation-scoped hash key learned from BlockStored
        # events (see apply()); consulted by the precise prefix producer
        self._lora_keys: dict[str, str] = {}
        self._lock = threading.RLock()
        # level 1: block_hash → level 2 (pod → entry), LRU on level 1.
        self._index: OrderedDict[int, OrderedDict[str, _PodEntry]] = OrderedDict()
        # reverse map pod → its keys, so AllBlocksCleared / pod removal are
        # O(keys-for-that-pod), not O(max_keys) under the lock.
        self._pod_keys: dict[str, set[int]] = {}
        self.stats = IndexStats()

    def resolve_lora_key(self, name: Optional[str]) -> Optional[str]:
        """Adapter name → generation-scoped 'name@digest' key learned from
        BlockStored events; falls back to the plain name before any engine has
        published blocks for the adapter (those hashes simply won't match yet)."""
        if not name:
            return name
        with self._lock:
            return self._lora_keys.get(name, name)

    def _drop(self, pod: str, block_hash: int) -> None:
        keys = self._pod_keys.get(pod)
        if keys is not None:
            keys.discard(block_hash)
            if not keys:
                del self._pod_keys[pod]

    # ---------------------------------------------------------------- events
    def apply(self, pod: str, event: KVEvent) -> None:
        with self._lock:
            self.stats.events_applied += 1
            if isinstance(event, BlockStored):
                if event.lora_id and "@" in event.lora_id:
                    # Engines hash blocks under the GENERATION-scoped adapter key
                    # 'name@<weights-digest>' (engine._lora_hash_key). Learn the
                    # mapping from the event stream so router-side producers hash
                    # with the same term — a plain-name hash would never match.
                    self._lora_keys[event.lora_id.split("@", 1)[0]] = event.lora_id
                for h in event.block_hashes:
                    self._store(pod, h, event.medium, spec_expiry=0.0)
                self.stats.blocks_stored += len(event.block_hashes)
            elif isinstance(event, BlockRemoved):
                for h in event.block_hashes:
                    pods = self._index.get(h)
                    if pods is None:
                        continue
                    entry = pods.get(pod)
                    # Only remove the matching tier: a gpu-tier removal right after
                    # an offload's BlockStored(cpu) must keep the CPU-tier entry.
                    if entry is not None:
                        entry.tiers.pop(event.medium, None)
                        if not entry.tiers:
                            del pods[pod]
                            self._drop(pod, h)
                            if not pods:
                                del self._index[h]
                self.stats.blocks_removed += len(event.block_hashes)
            elif isinstance(event, AllBlocksCleared):
                for h in self._pod_keys.pop(pod, ()):
                    pods = self._index.get(h)
                    if pods is not None:
                        pods.pop(pod, None)
                        if not pods:
                            del self._index[h]
                self.stats.clears += 1

    def apply_batch(self, pod: str, events: Sequence[KVEvent]) -> None:
        for ev in events:
            self.apply(pod, ev)

    def _store(self, pod: str, block_hash: int, tier: str, spec_expiry: float) -> None:
        pods = self._index.get(block_hash)
        if pods is None:
            pods = self._index[block_hash] = OrderedDict()
        existing = pods.get(pod)
        if existing is not None:
            cur = existing.tiers.get(tier)
            # a confirmed tier entry never downgrades back to speculative
            if spec_expiry == 0.0 or cur is None or cur != 0.0:
                existing.tiers[tier] = spec_expiry
            pods.move_to_end(pod)
        else:
            pods[pod] = _PodEntry(tiers={tier: spec_expiry})
            self._pod_keys.setdefault(pod, set()).add(block_hash)
            while len(pods) > self.max_pods_per_key:
                evicted_pod, _ = pods.popitem(last=False)
                self._drop(evicted_pod, block_hash)
                self.stats.evictions += 1
        self._index.move_to_end(block_hash)
        while len(self._index) > self.max_keys:
            evicted_hash, evicted_pods = self._index.popitem(last=False)
            for p in evicted_pods:
                self._drop(p, evicted_hash)
            self.stats.evictions += 1

    # ------------------------------------------------------------- speculative
    def add_speculative(self, pod: str, block_hashes: Sequence[int],
                        tier: str = MEDIUM_HBM) -> None:
        """Insert short-TTL entries after a scheduling pick (kv-indexer.md:104-143)."""
        expiry = time.monotonic() + self.spec_ttl
        with self._lock:
            for h in block_hashes:
                self._store(pod, h, tier, spec_expiry=expiry)
            self.stats.speculative_inserts += len(block_hashes)

    # ----------------------------------------------------------------- lookup
    def lookup(self, block_hashes: Sequence[int],
               pods: Sequence[str]) -> dict[str, PrefixMatch]:
        """Longest-consecutive-prefix walk per candidate pod (HOT: every request)."""
        now = time.monotonic()
        out = {p: PrefixMatch() for p in pods}
        live = set(pods)
        with self._lock:
            self.stats.lookups += 1
            for h in block_hashes:
                if not live:
                    break
                entry_pods = self._index.get(h)
                if not entry_pods:
                    break
                matched_any = False
                for p in list(live):
                    e = entry_pods.get(p)
                    if e is None or not e.live(now):
                        live.discard(p)
                        continue
                    m = out[p]
                    m.blocks += 1
                    m.weighted += e.best_weight(self.tier_weights, now)
                    matched_any = True
                if not matched_any:
                    break
        return out

    def pods_for_block(self, block_hash: int) -> dict[str, list[str]]:
        now = time.monotonic()
        with self._lock:
            pods = self._index.get(block_hash) or {}
            return {
                p: [t for t, exp in e.tiers.items() if exp == 0.0 or now < exp]
                for p, e in pods.items() if e.live(now)
            }

    def remove_pod(self, pod: str) -> None:
        """Drop every entry for a departed pod (endpoint removed from the pool)."""
        with self._lock:
            for h in self._pod_keys.pop(pod, ()):
                pods = self._index.get(h)
                if pods is not None:
                    pods.pop(pod, None)
                    if not pods:
                        del self._index[h]

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)
