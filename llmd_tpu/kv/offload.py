"""KV offload connector: HBM → CPU → FS tiering for the paged cache.

Parity: reference kv-offloader.md:27-118 (native OffloadingConnector: DMA-staged
GPU→CPU offload with a bounded CPU budget) and the TPU path the reference already
ships — ``TPUOffloadConnector`` (``tpu_inference.offload.tpu_offload_connector``,
``kv_role: kv_both``, env ``TPU_OFFLOAD_NUM_CPU_CHUNKS`` / ``STAGING_BLOCKS`` —
guides/agentic-serving/modelserver/tpu/vllm/patch-vllm.yaml:39,47-50).

TPU-native shape: the device cache is one flat layer-folded page pool
``[L*P, ps, 2Hk, Dhp]``; a logical KV page is the row set ``{l*P + page_id}``.
Offload is one host gather of those rows; reload is one batched scatter back
compiled once with a fixed staging width
so XLA never retraces. Evicted-but-offloaded blocks keep earning prefix-cache hits:
the engine checks HBM, then CPU, then FS at admission — tiered exactly like the
reference's gpu→cpu→fs chain, and each transition emits KV events with the right
``medium`` so the router's tier-weighted scoring stays truthful.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from llmd_tpu.core.kv_events import (
    BlockRemoved,
    BlockStored,
    KVEvent,
    MEDIUM_CPU,
    MEDIUM_FS,
)
from llmd_tpu.kv.fs_backend import FSKVBackend


class CPUOffloadStore:
    """Bounded host-memory KV block store with LRU demotion to an optional FS tier."""

    def __init__(
        self,
        capacity_blocks: int,
        fs_backend: Optional[FSKVBackend] = None,
        event_sink: Optional[Callable[[list[KVEvent]], None]] = None,
        metrics=None,
    ) -> None:
        self.capacity = capacity_blocks
        self.fs = fs_backend
        self.event_sink = event_sink
        self.metrics = metrics  # EngineMetrics (obs.metrics) or None
        self._blocks: OrderedDict[int, np.ndarray] = OrderedDict()
        self._pending_fs: dict[int, object] = {}  # hash → in-flight demotion future
        self.saves = 0
        self.loads = 0
        self.demotions = 0

    def _emit(self, events: list[KVEvent]) -> None:
        if self.event_sink and events:
            self.event_sink(events)

    def put(self, block_hash: int, array: np.ndarray) -> None:
        if block_hash in self._blocks:
            self._blocks.move_to_end(block_hash)
            return
        self._blocks[block_hash] = array
        self.saves += 1
        if self.metrics is not None:
            self.metrics.offload_transfer_bytes.labels(
                direction="save").observe(array.nbytes)
        events: list[KVEvent] = [BlockStored(
            block_hashes=[block_hash], parent_block_hash=None, token_ids=[],
            block_size=0, medium=MEDIUM_CPU,
        )]
        while len(self._blocks) > self.capacity:
            old_hash, old_arr = self._blocks.popitem(last=False)
            if self.metrics is not None:
                self.metrics.offload_evictions.inc()
            events.append(BlockRemoved(block_hashes=[old_hash], medium=MEDIUM_CPU))
            if self.fs is not None:
                # async demotion: keeps the engine step loop off the disk; the popped
                # array stays alive in the future's closure until written
                fut = self.fs.put_async(old_hash, old_arr)
                self._pending_fs[old_hash] = fut
                fut.add_done_callback(
                    lambda _f, h=old_hash: self._pending_fs.pop(h, None)
                )
                self.demotions += 1
                events.append(BlockStored(
                    block_hashes=[old_hash], parent_block_hash=None, token_ids=[],
                    block_size=0, medium=MEDIUM_FS,
                ))
        self._emit(events)

    def get(self, block_hash: int) -> Optional[np.ndarray]:
        arr = self._blocks.get(block_hash)
        if arr is not None:
            self._blocks.move_to_end(block_hash)
            self.loads += 1
            self._record_hit(arr)
            return arr
        if self.fs is not None:
            fut = self._pending_fs.get(block_hash)
            if fut is not None:
                try:
                    fut.result()  # wait out an in-flight demotion write
                except Exception:
                    self._record_miss()
                    return None
            arr = self.fs.get(block_hash)
            if arr is not None:
                self.loads += 1
                self._record_hit(arr)
                return arr
        self._record_miss()
        return None

    def _record_hit(self, arr: np.ndarray) -> None:
        if self.metrics is not None:
            self.metrics.offload_hits.inc()
            self.metrics.offload_transfer_bytes.labels(
                direction="load").observe(arr.nbytes)

    def _record_miss(self) -> None:
        if self.metrics is not None:
            self.metrics.offload_misses.inc()

    def contains(self, block_hash: int) -> bool:
        if block_hash in self._blocks:
            return True
        if self.fs is None:
            return False
        return block_hash in self._pending_fs or self.fs.contains(block_hash)

    def __len__(self) -> int:
        return len(self._blocks)


class KVOffloadConnector:
    """Engine-side connector: page eviction hook + batched reload into the cache.

    The engine wires ``on_evict`` into the PageAllocator (called just before a cached
    page is recycled) and calls ``match``/``load_into_cache`` at admission. Reloads
    are padded to a fixed ``staging_blocks`` width so the jitted scatter compiles
    once (STAGING_BLOCKS knob of the reference TPU connector).
    """

    def __init__(
        self,
        num_cpu_chunks: int,
        staging_blocks: int = 16,
        fs_backend: Optional[FSKVBackend] = None,
        event_sink: Optional[Callable[[list[KVEvent]], None]] = None,
        pages_per_layer: Optional[int] = None,
        metrics=None,
        flight=None,
    ) -> None:
        self.store = CPUOffloadStore(num_cpu_chunks, fs_backend, event_sink,
                                     metrics=metrics)
        self.flight = flight  # obs.events.FlightRecorder or None
        self.staging_blocks = max(1, staging_blocks)
        # cache is the flat layer-folded pool [L*P, ps, 2Hk, Dhp]; P is needed to
        # gather one logical page's rows across layers. None = single-layer pool.
        self.pages_per_layer = pages_per_layer
        self._load_fn = None  # jitted, built lazily (needs cache shape)
        # optional durable-tier tee (kv/writeback.py): eviction/demotion
        # paths re-offer their already-materialized host bytes, so the
        # cluster store rides the same device reads the local tier pays for
        self.writeback = None

    def _layer_rows(self, cache, page_id):
        """Row indices of logical page `page_id` across layers: l*P + page_id."""
        P = self.pages_per_layer or cache.shape[0]
        L = cache.shape[0] // P
        return np.arange(L) * P + page_id

    # ------------------------------------------------------------------ evict
    def on_evict(self, cache, block_hash: int, page_id: int) -> None:
        """Backstop for demand outrunning the proactive drain: copy an
        about-to-be-recycled page HBM→host (one per-page device sync — the batched
        ``demote_batch`` path is the steady-state eviction route)."""
        block = np.asarray(cache[self._layer_rows(cache, page_id)])
        self.store.put(block_hash, block)
        if self.writeback is not None:
            self.writeback.offer([block_hash], block[None])
        if self.flight is not None:
            self.flight.record_system("kv_offload", n_blocks=1, path="evict")

    def demote_batch(self, cache, pairs: list[tuple[int, int]]) -> None:
        """Offload a batch of demoted pages in ONE device-to-host gather.

        ``pairs`` come from PageAllocator.demote_lru; the pages are already on the
        free list but their contents are intact until reallocated and rewritten,
        which cannot happen before this returns (single step thread)."""
        if not pairs:
            return
        import jax
        import jax.numpy as jnp

        pids = np.asarray([pid for _, pid in pairs], np.int32)
        rows = np.stack([self._layer_rows(cache, pid) for pid in pids], axis=1)  # [L, n]
        arr = np.asarray(jax.device_get(cache[jnp.asarray(rows)]))  # [L, n, ps, 2Hk, Dhp]
        arr = np.moveaxis(arr, 1, 0)
        for (h, _), block in zip(pairs, arr):
            self.store.put(h, np.ascontiguousarray(block))
        if self.writeback is not None:
            self.writeback.offer([h for h, _ in pairs],
                                 np.ascontiguousarray(arr))
        if self.flight is not None:
            self.flight.record_system("kv_offload", n_blocks=len(pairs),
                                      path="drain")

    # ------------------------------------------------------------------ match
    def match_suffix(self, block_hashes: list[int]) -> int:
        """How many consecutive leading blocks the offload tiers hold."""
        n = 0
        for h in block_hashes:
            if not self.store.contains(h):
                break
            n += 1
        return n

    # ------------------------------------------------------------------ reload
    def load_into_cache(self, cache, block_hashes: list[int], page_ids: list[int],
                        request_id: Optional[str] = None):
        """Scatter offloaded blocks back into freshly allocated pages.

        Returns (new_cache, n_loaded) — n_loaded may stop short if a block vanished
        (FS evictor raced us); callers recompute the remainder.
        ``request_id`` attributes the reload to the admitting request's
        flight-recorder timeline.
        """
        import jax
        import jax.numpy as jnp

        if self._load_fn is None:
            Ptot = cache.shape[0]
            P = self.pages_per_layer or Ptot
            L = Ptot // P

            def _load(cache, blocks, pids):
                # pids -1 → out-of-bounds index dropped by the scatter (padding)
                rows = jnp.arange(L)[:, None] * P + pids[None, :]  # [L, n]
                rows = jnp.where(pids[None, :] >= 0, rows, Ptot)
                dev = jnp.moveaxis(blocks, 0, 1)
                if cache.dtype == jnp.float8_e4m3fn and dev.dtype != cache.dtype:
                    # wider-dtype blob (pre-fp8 tier contents): e4m3 has no
                    # inf — clamp like the engine write path (transformer._FP8_MAX)
                    from llmd_tpu.models.transformer import _FP8_MAX

                    dev = jnp.clip(dev.astype(jnp.float32), -_FP8_MAX, _FP8_MAX)
                return cache.at[rows].set(dev.astype(cache.dtype), mode="drop")

            self._load_fn = jax.jit(_load, donate_argnums=(0,))

        S = self.staging_blocks
        P = self.pages_per_layer or cache.shape[0]
        L = cache.shape[0] // P
        block_shape = (L,) + cache.shape[1:]  # [L, ps, 2Hk/f, Dhp]
        arrays: list[np.ndarray] = []
        for h in block_hashes:
            arr = self.store.get(h)
            if arr is None:
                break
            if arr.shape != block_shape:
                # blob persisted under a different pool layout (kv_layout /
                # restart across an upgrade): hashes fold tokens only, so the
                # match can't see this — treat as a miss (callers recompute)
                # rather than crash the step loop on a shape-mismatched scatter
                break
            arrays.append(arr)
        n_loaded = len(arrays)
        for start in range(0, n_loaded, S):
            group = arrays[start : start + S]
            pids = np.full((S,), -1, np.int32)
            pids[: len(group)] = page_ids[start : start + len(group)]
            stacked = np.zeros((S,) + block_shape, dtype=group[0].dtype)
            for i, a in enumerate(group):
                stacked[i] = a
            cache = self._load_fn(cache, stacked, pids)
        if self.flight is not None and request_id and n_loaded:
            self.flight.record(request_id, "kv_reload", n_blocks=n_loaded,
                               bytes=sum(a.nbytes for a in arrays))
        return cache, n_loaded
