"""KV-cache management plane.

Re-implements the reference's KV plane (docs/architecture/advanced/kv-management/):

- ``llmd_tpu.kv.indexer``    — the KV-Cache Indexer: a two-level LRU index of which pod
  holds which KV block on which tier, fed by KV events (kv-indexer.md:59-151).
- ``llmd_tpu.kv.subscriber`` — ZMQ event subscription manager (centralized or
  pod-discovery delivery, kv-indexer.md:67-87).
- ``llmd_tpu.kv.plugins``    — router plugins: token-producer,
  precise-prefix-cache-producer, precise-prefix-cache-scorer.
- ``llmd_tpu.kv.offload``    — TPU offload connector: HBM→CPU tiering
  (kv-offloader.md:27-118; TPUOffloadConnector analogue).
- ``llmd_tpu.kv.fs_backend`` — POSIX-FS KV block store (llmd_fs_backend analogue,
  kv-offloader.md:120-169).
- ``llmd_tpu.kv.connector_api`` — out-of-tree connector seam (LMCache/Mooncake/KVBM
  role, kv-offloader.md:70-100) with the in-memory reference connector.
- ``llmd_tpu.kv.remote_store`` — remote content-addressed block store over TCP
  (the InfiniStore role) + its engine-side connector.
"""

from llmd_tpu.kv.connector_api import (  # noqa: F401
    KVConnectorBase,
    build_kv_connector,
    register_kv_connector,
)
from llmd_tpu.kv.indexer import KVBlockIndex  # noqa: F401
