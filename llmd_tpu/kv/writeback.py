"""Durable prefix tier: write-back flush queue + hardened store client (N9).

The remote store (kv/remote_store.py) gives the cluster a content-addressed
block store that outlives any replica; this module is the serving-path half
that makes it a real tier:

- ``DurableStoreClient`` — per-op deadlines, full-jitter retry, and a
  PR-3-shaped circuit breaker (consecutive failures OR windowed failure rate
  opens; cooldown -> half-open single trial; success closes). Store down,
  slow, or corrupt degrades to today's behavior — never a client error.
- ``WritebackQueue`` — async bounded flush queue feeding prefix blocks to the
  store on eviction and drain. ``offer`` is non-blocking (drop-oldest on
  overflow) so the step loop never waits on DCN; ``flush_for_drain`` empties
  it synchronously under a hard budget so PoolController._drain retires on
  time even against a hung store (the remainder is counted ``abandoned``).
- ``stage_resident_blocks`` — cheap device-side gather of the resident prefix
  working set (MLA engines hold latent pages, so flushed bytes stay honest).

Config comes from ``LLMD_KV_DURABLE_*`` (deploy/ENV_VARS.md); the tier is off
unless ``LLMD_KV_DURABLE_STORE=host:port`` is set.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from llmd_tpu.kv.remote_store import (_recv_exact, _recv_frame, _send_frame,
                                      resolve_dtype, verify_crc_prefix)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class DurableStoreConfig:
    host: str = ""
    port: int = 0
    op_timeout_s: float = 2.0      # bulk get/put deadline per attempt
    probe_timeout_s: float = 0.25  # admission-adjacent probe deadline
    retries: int = 2               # extra attempts after the first (bulk only)
    backoff_ms: float = 25.0       # full-jitter base
    backoff_max_ms: float = 250.0  # full-jitter cap
    breaker_failures: int = 3      # consecutive failures that open the breaker
    breaker_window: int = 20       # sliding window of recent outcomes
    breaker_failure_rate: float = 0.5
    breaker_min_volume: int = 10   # rate check needs at least this many samples
    breaker_cooldown_s: float = 10.0
    queue_blocks: int = 512        # flush-queue bound (blocks, not entries)
    drain_budget_s: float = 5.0    # hard cap on drain-time synchronous flush

    @property
    def enabled(self) -> bool:
        return bool(self.host) and self.port > 0

    @classmethod
    def from_env(cls) -> "DurableStoreConfig":
        addr = os.environ.get("LLMD_KV_DURABLE_STORE", "")
        host, port = "", 0
        if addr:
            h, _, p = addr.rpartition(":")
            try:
                host, port = (h or "127.0.0.1"), int(p)
            except ValueError:
                host, port = "", 0
        return cls(
            host=host, port=port,
            op_timeout_s=_env_f("LLMD_KV_DURABLE_OP_TIMEOUT_S", 2.0),
            probe_timeout_s=_env_f("LLMD_KV_DURABLE_PROBE_TIMEOUT_S", 0.25),
            retries=max(0, _env_i("LLMD_KV_DURABLE_RETRIES", 2)),
            backoff_ms=_env_f("LLMD_KV_DURABLE_BACKOFF_MS", 25.0),
            backoff_max_ms=_env_f("LLMD_KV_DURABLE_BACKOFF_MAX_MS", 250.0),
            breaker_failures=max(
                1, _env_i("LLMD_KV_DURABLE_BREAKER_FAILURES", 3)),
            breaker_window=max(1, _env_i("LLMD_KV_DURABLE_BREAKER_WINDOW", 20)),
            breaker_failure_rate=_env_f("LLMD_KV_DURABLE_BREAKER_RATE", 0.5),
            breaker_min_volume=max(
                1, _env_i("LLMD_KV_DURABLE_BREAKER_MIN_VOLUME", 10)),
            breaker_cooldown_s=_env_f("LLMD_KV_DURABLE_BREAKER_COOLDOWN_S",
                                      10.0),
            queue_blocks=max(1, _env_i("LLMD_KV_DURABLE_QUEUE_BLOCKS", 512)),
            drain_budget_s=_env_f("LLMD_KV_DURABLE_DRAIN_BUDGET_S", 5.0),
        )


class DurableStoreClient:
    """KVS1 client with deadlines, full-jitter retry, and a circuit breaker.

    The breaker is the router's PR-3 shape (resilience.py EndpointBreaker),
    scoped to one store: consecutive-failure fast path for a dead store, a
    windowed failure-rate path for a flapping one, and a half-open single
    trial after cooldown so recovery is automatic.
    """

    def __init__(self, cfg: DurableStoreConfig) -> None:
        self.cfg = cfg
        self._lock = threading.Lock()
        self._rng = random.Random()
        # breaker state — guarded-by: _lock
        self._state = "closed"        # closed | open | half_open
        self._consec = 0
        self._window: list = []       # recent outcomes, True = failure
        self._open_until = 0.0
        self._half_open_inflight = False
        # guarded-by: _lock
        self.stats = {"gets": 0, "puts": 0, "probes": 0, "errors": 0,
                      "corrupt": 0, "breaker_trips": 0, "breaker_skips": 0}

    # -- breaker -----------------------------------------------------------
    def _allow(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if now < self._open_until:
                    self.stats["breaker_skips"] += 1
                    return False
                self._state = "half_open"
                self._half_open_inflight = False
            # half-open: exactly one trial probes the store; the rest skip
            if self._half_open_inflight:
                self.stats["breaker_skips"] += 1
                return False
            self._half_open_inflight = True
            return True

    def _record(self, ok: bool) -> None:
        with self._lock:
            if self._state == "half_open":
                self._half_open_inflight = False
                if ok:
                    self._state = "closed"
                    self._consec = 0
                    self._window.clear()
                else:
                    self._state = "open"
                    self._open_until = (time.monotonic()
                                        + self.cfg.breaker_cooldown_s)
                    self.stats["errors"] += 1
                return
            self._window.append(not ok)
            if len(self._window) > self.cfg.breaker_window:
                del self._window[: len(self._window) - self.cfg.breaker_window]
            if ok:
                self._consec = 0
                return
            self.stats["errors"] += 1
            self._consec += 1
            rate_open = (len(self._window) >= self.cfg.breaker_min_volume
                         and (sum(self._window) / len(self._window)
                              >= self.cfg.breaker_failure_rate))
            if self._consec >= self.cfg.breaker_failures or rate_open:
                self._state = "open"
                self._open_until = (time.monotonic()
                                    + self.cfg.breaker_cooldown_s)
                self.stats["breaker_trips"] += 1

    def breaker_state(self) -> float:
        """0 closed, 0.5 half-open, 1 open — shaped for a gauge."""
        with self._lock:
            return {"closed": 0.0, "half_open": 0.5, "open": 1.0}[self._state]

    def _jitter_s(self, attempt: int) -> float:
        cap = min(self.cfg.backoff_ms * (2 ** attempt),
                  self.cfg.backoff_max_ms)
        return self._rng.uniform(0.0, cap) / 1000.0

    # -- wire --------------------------------------------------------------
    def _rpc(self, header: dict, payload: bytes = b"",
             timeout: Optional[float] = None) -> tuple[dict, bytes]:
        with socket.create_connection(
                (self.cfg.host, self.cfg.port),
                timeout=timeout or self.cfg.op_timeout_s) as conn:
            _send_frame(conn, header, payload)
            resp, _ = _recv_frame(conn)
            body = (_recv_exact(conn, int(resp["nbytes"]))
                    if resp.get("nbytes") else b"")
            return resp, body

    # -- ops ---------------------------------------------------------------
    def probe(self, hashes: list[int]) -> int:
        """Consecutive found prefix; 0 on any failure. No retry — this sits
        next to routing decisions, so it pays at most one tight deadline."""
        if not self._allow():
            return 0
        with self._lock:
            self.stats["probes"] += 1
        try:
            resp, _ = self._rpc({"op": "probe", "hashes": list(hashes)},
                                timeout=self.cfg.probe_timeout_s)
            if "error" in resp:
                raise ValueError(resp["error"])
            self._record(ok=True)
            return int(resp.get("found", 0))
        except (OSError, ConnectionError, KeyError, ValueError):
            self._record(ok=False)
            return 0

    def get(self, hashes: list[int]) -> tuple[int, Optional[np.ndarray], str]:
        """Fetch the consecutive verified prefix of ``hashes``.

        Returns ``(n, blocks[n, L, ...] | None, outcome)`` with outcome in
        {ok, miss, corrupt, error, breaker_open}. A checksum mismatch
        truncates to the verified prefix (still usable) and counts as a
        path failure so a corrupting store trips the breaker.
        """
        if not self._allow():
            return 0, None, "breaker_open"
        with self._lock:
            self.stats["gets"] += 1
        for attempt in range(self.cfg.retries + 1):
            try:
                resp, body = self._rpc({"op": "get", "hashes": list(hashes)})
                if "error" in resp:
                    raise ValueError(resp["error"])
                n = int(resp.get("found", 0))
                if n == 0:
                    self._record(ok=True)
                    return 0, None, "miss"
                good = verify_crc_prefix(body, n, resp.get("crc"))
                per = len(body) // n
                if good < n:
                    with self._lock:
                        self.stats["corrupt"] += 1
                    self._record(ok=False)
                    if good == 0:
                        return 0, None, "corrupt"
                else:
                    self._record(ok=True)
                blocks = np.frombuffer(
                    body[: good * per],
                    dtype=resolve_dtype(resp["dtype"])).reshape(
                    (good, *resp["shape"]))
                return good, blocks, ("ok" if good == n else "corrupt")
            except (OSError, ConnectionError, KeyError, ValueError):
                self._record(ok=False)
                if attempt < self.cfg.retries and self._allow_retry():
                    time.sleep(self._jitter_s(attempt))
                else:
                    break
        return 0, None, "error"

    def put(self, hashes: list[int], blocks: np.ndarray,
            timeout: Optional[float] = None,
            retries: Optional[int] = None) -> str:
        """Store ``blocks[n, L, ...]`` under ``hashes``; outcome in
        {ok, error, breaker_open}. ``timeout``/``retries`` let drain-time
        flushing clamp each attempt to the remaining budget."""
        if not self._allow():
            return "breaker_open"
        with self._lock:
            self.stats["puts"] += 1
        arr = np.ascontiguousarray(blocks)
        tries = self.cfg.retries if retries is None else retries
        for attempt in range(tries + 1):
            try:
                resp, _ = self._rpc(
                    {"op": "put", "hashes": [int(h) for h in hashes],
                     "dtype": str(arr.dtype), "shape": list(arr.shape[1:]),
                     "nbytes": arr.nbytes}, arr.tobytes(), timeout=timeout)
                if "error" in resp:
                    raise ValueError(resp["error"])
                self._record(ok=True)
                return "ok"
            except (OSError, ConnectionError, KeyError, ValueError):
                self._record(ok=False)
                if attempt < tries and self._allow_retry():
                    time.sleep(self._jitter_s(attempt))
                else:
                    break
        return "error"

    def _allow_retry(self) -> bool:
        # retrying into an open breaker just burns the backoff sleep
        with self._lock:
            return self._state != "open"


class WritebackQueue:
    """Bounded async flush queue: prefix blocks -> durable store.

    ``offer`` runs on eviction/drain paths adjacent to the step loop, so it
    only appends under a condition variable — never any socket or device
    work. The daemon worker does the DCN puts. Overflow drops the OLDEST
    entries: under pressure the freshest working set is the one a future
    replica will want back.
    """

    def __init__(self, client: DurableStoreClient, max_blocks: int = 512,
                 on_flush: Optional[Callable[[str, int], None]] = None) -> None:
        self.client = client
        self.max_blocks = max_blocks
        self.on_flush = on_flush
        self._cond = threading.Condition()
        self._q: deque = deque()  # guarded-by: _cond — (hashes, blocks)
        self._depth = 0           # guarded-by: _cond — total queued blocks
        self._stopped = False     # guarded-by: _cond
        # guarded-by: _cond — all in BLOCKS, matching the flush counter
        self.counts = {"ok": 0, "error": 0, "dropped": 0, "abandoned": 0}
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="kv-writeback")
        self._thread.start()

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def offer(self, hashes: list[int], blocks: np.ndarray) -> bool:
        """Enqueue without blocking; drop-oldest keeps the bound."""
        n = len(hashes)
        if n == 0:
            return True
        dropped = 0
        with self._cond:
            if self._stopped:
                return False
            self._q.append(([int(h) for h in hashes], blocks))
            self._depth += n
            while self._depth > self.max_blocks and len(self._q) > 1:
                old_hashes, _old = self._q.popleft()
                self._depth -= len(old_hashes)
                dropped += len(old_hashes)
            self.counts["dropped"] += dropped
            self._cond.notify()
        if dropped and self.on_flush is not None:
            try:
                self.on_flush("dropped", dropped)
            except Exception:
                pass
        return True

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopped:
                    self._cond.wait(timeout=0.5)
                if not self._q:
                    if self._stopped:
                        return
                    continue
                hashes, blocks = self._q.popleft()
                self._depth -= len(hashes)
            self._flush_one(hashes, blocks)

    def _flush_one(self, hashes: list[int], blocks) -> str:
        outcome = self.client.put(hashes, np.asarray(blocks))
        key = "ok" if outcome == "ok" else "error"
        with self._cond:
            self.counts[key] += len(hashes)
        if self.on_flush is not None:
            try:
                self.on_flush(key, len(hashes))
            except Exception:
                pass  # observability must not break the flush path
        return outcome

    def flush_for_drain(self, budget_s: float) -> tuple[int, int]:
        """Synchronously empty the queue within ``budget_s`` seconds.

        Each put attempt is clamped to the remaining budget with no retries,
        and an open breaker fails instantly — so a hung store cannot push
        drain past its timeout. Every block that does not land — a failed
        drain-time put or whatever is still queued at the deadline — is
        counted ``abandoned`` (drain accounting: the replica retires and
        those blocks are gone). Returns (flushed_blocks, abandoned_blocks).
        """
        deadline = time.monotonic() + max(0.0, budget_s)
        flushed = 0
        abandoned = 0
        while True:
            remaining = deadline - time.monotonic()
            with self._cond:
                if not self._q:
                    break
                if remaining <= 0.05:
                    abandoned += self._depth
                    self._q.clear()
                    self._depth = 0
                    break
                hashes, blocks = self._q.popleft()
                self._depth -= len(hashes)
            outcome = self.client.put(
                hashes, np.asarray(blocks),
                timeout=min(self.client.cfg.op_timeout_s, remaining),
                retries=0)
            if outcome == "ok":
                flushed += len(hashes)
                with self._cond:
                    self.counts["ok"] += len(hashes)
                if self.on_flush is not None:
                    try:
                        self.on_flush("ok", len(hashes))
                    except Exception:
                        pass
            else:
                abandoned += len(hashes)
        if abandoned:
            with self._cond:
                self.counts["abandoned"] += abandoned
            if self.on_flush is not None:
                try:
                    self.on_flush("abandoned", abandoned)
                except Exception:
                    pass
        return flushed, abandoned

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=2.0)


def stage_resident_blocks(engine, max_blocks: int) -> tuple[list[int], list]:
    """Device-side gather of up to ``max_blocks`` resident prefix blocks.

    MUST run under the engine lock (run_locked) — it only slices the cache
    into staged device parts, the cheap half of the offload split; call
    ``drain_staged(parts)`` OFF the lock to materialize host bytes. Takes the
    tail of the prefix-cache insertion order, i.e. the freshest blocks.
    MLA engines store latent pages in the cache, so the staged bytes are
    already the compact latent layout — nothing extra to do here.
    """
    from llmd_tpu.disagg.transfer import stage_pages

    pairs = list(engine.alloc.cached.items())[-max_blocks:]
    if not pairs:
        return [], []
    hashes = [int(h) for h, _pid in pairs]
    pids = [pid for _h, pid in pairs]
    parts = stage_pages(engine.cache, pids, engine.cfg.num_pages,
                        engine.cfg.offload_staging_blocks)
    return hashes, parts
