"""KV-event subscription manager: ZMQ SUB side of the KV plane.

Parity: reference kv-indexer.md:67-87 — two delivery modes:

- **pod-discovery** (default, active-active HA): each engine pod binds a PUB socket;
  every router replica subscribes to every pod it discovers in the endpoint pool, so
  replicas converge independently (no leader needed).
- **centralized**: the router binds one SUB socket and engines connect their PUBs to it
  (EPP binds :5557 in the reference).

Topic format ``kv@<pod_addr>@<model>`` (precise-prefix-cache-routing/README.md:300-307);
``topic_filter`` subscribes a prefix. Sequence-number gaps are counted (events are
fire-and-forget PUB/SUB; a gap means missed events and only costs routing precision).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import zmq
import zmq.asyncio

from llmd_tpu.core.endpoint import Endpoint, EndpointPool
from llmd_tpu.core.kv_events import decode_event_batch
from llmd_tpu.kv.indexer import KVBlockIndex

log = logging.getLogger(__name__)

LABEL_KV_EVENTS_ADDR = "kv_events_address"  # full "host:port" override label
LABEL_KV_EVENTS_PORT = "kv_events_port"  # port-only label (host = endpoint host)


class KVEventSubscriberManager:
    """Maintains one SUB socket per discovered pod, feeding the shared index."""

    def __init__(
        self,
        index: KVBlockIndex,
        pool: Optional[EndpointPool] = None,
        topic_filter: str = "kv@",
        default_events_port: Optional[int] = None,
        bind_port: Optional[int] = None,  # centralized mode: bind instead of connect
    ) -> None:
        self.index = index
        self.pool = pool
        self.topic_filter = topic_filter
        self.default_events_port = default_events_port
        self.bind_port = bind_port
        self._zctx: Optional[zmq.asyncio.Context] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._tasks: dict[str, asyncio.Task] = {}
        self._central_task: Optional[asyncio.Task] = None
        self._last_seq: dict[str, int] = {}
        self._stopping = False
        self.seq_gaps = 0
        self.batches_received = 0

    # ---------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._zctx = zmq.asyncio.Context()
        self._loop = asyncio.get_running_loop()
        if self.bind_port is not None:
            self._central_task = self._loop.create_task(self._run_central())
            return
        if self.pool is not None:
            self.pool.subscribe(self._on_pool_event)
            for ep in self.pool.list():
                self._maybe_subscribe(ep)

    async def stop(self) -> None:
        self._stopping = True
        if self.pool is not None:
            self.pool.unsubscribe(self._on_pool_event)
        for t in list(self._tasks.values()) + ([self._central_task] if self._central_task else []):
            t.cancel()
        for t in list(self._tasks.values()) + ([self._central_task] if self._central_task else []):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        self._loop = None
        if self._zctx is not None:
            self._zctx.term()
            self._zctx = None

    # ---------------------------------------------------------------- discovery
    def _events_address(self, ep: Endpoint) -> Optional[str]:
        addr = ep.labels.get(LABEL_KV_EVENTS_ADDR)
        if addr:
            return addr
        port = ep.labels.get(LABEL_KV_EVENTS_PORT) or self.default_events_port
        if port:
            return f"{ep.host}:{port}"
        return None

    def _on_pool_event(self, event: str, ep: Endpoint) -> None:
        if event == "added":
            self._maybe_subscribe(ep)
        elif event == "removed":
            task = self._tasks.pop(ep.address, None)
            if task:
                task.cancel()
            self.index.remove_pod(ep.address)

    def _maybe_subscribe(self, ep: Endpoint) -> None:
        if ep.address in self._tasks or self._loop is None:
            return
        zaddr = self._events_address(ep)
        if zaddr is None:
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            self._tasks[ep.address] = self._loop.create_task(self._run_pod(ep.address, zaddr))
        else:
            # pool callbacks may fire from a discovery thread (k8s watch); hop onto
            # the subscriber's loop — create_task is not thread-safe.
            loop = self._loop

            def _spawn(address: str = ep.address, z: str = zaddr) -> None:
                # guard against stop() racing the hop: _stopping flips before
                # tasks are cancelled, so nothing spawns after that point
                if address not in self._tasks and not self._stopping and self._zctx is not None:
                    self._tasks[address] = loop.create_task(self._run_pod(address, z))

            loop.call_soon_threadsafe(_spawn)

    def subscribe_pod(self, pod_address: str, zmq_address: str) -> None:
        """Explicit subscription (tests / static wiring)."""
        if pod_address in self._tasks:
            return
        self._tasks[pod_address] = asyncio.get_running_loop().create_task(
            self._run_pod(pod_address, zmq_address)
        )

    # ---------------------------------------------------------------- receive
    def _handle(self, topic: bytes, payload: bytes) -> None:
        # topic kv@<pod_addr>@<model> — the pod address inside the topic is
        # authoritative (centralized mode has no per-socket pod identity).
        parts = topic.decode(errors="replace").split("@")
        pod = parts[1] if len(parts) >= 2 else "?"
        seq, events = decode_event_batch(payload)
        last = self._last_seq.get(pod)
        if last is not None and seq > last + 1:
            self.seq_gaps += seq - last - 1
        self._last_seq[pod] = seq
        self.index.apply_batch(pod, events)
        self.batches_received += 1

    async def _run_pod(self, pod_address: str, zmq_address: str) -> None:
        sock = None
        try:
            sock = self._zctx.socket(zmq.SUB)
            sock.setsockopt(zmq.SUBSCRIBE, self.topic_filter.encode())
            sock.connect(f"tcp://{zmq_address}")
            while True:
                topic, payload = await sock.recv_multipart()
                try:
                    self._handle(topic, payload)
                except Exception:
                    log.exception("bad KV event batch from %s", pod_address)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("KV subscription to %s (%s) failed", pod_address, zmq_address)
        finally:
            if sock is not None:
                sock.close(0)

    async def _run_central(self) -> None:
        sock = None
        try:
            sock = self._zctx.socket(zmq.SUB)
            sock.setsockopt(zmq.SUBSCRIBE, self.topic_filter.encode())
            if self.bind_port == 0:
                self.bind_port = sock.bind_to_random_port("tcp://0.0.0.0")
            else:
                sock.bind(f"tcp://0.0.0.0:{self.bind_port}")
            while True:
                topic, payload = await sock.recv_multipart()
                try:
                    self._handle(topic, payload)
                except Exception:
                    log.exception("bad KV event batch (centralized)")
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("centralized KV subscription on :%s failed", self.bind_port)
        finally:
            if sock is not None:
                sock.close(0)
