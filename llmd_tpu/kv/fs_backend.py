"""POSIX-filesystem KV block store — the llmd_fs_backend analogue.

Parity: reference kv-offloader.md:120-169,183-207 — KV blocks stored as files on any
shared POSIX FS (CephFS/Lustre/NVMe-local), the **directory is the index** (no extra
metadata service: presence of the file = presence of the block), writes are
atomic (tmp + rename) so concurrent writers of the same content-addressed block are
idempotent, and there is **no internal eviction** — an external evictor
(`evict_to_bytes`, the PVC Evictor analogue) trims by LRU mtime.

Blocks are content-addressed by their chained block hash, sharded into 256 prefix
directories to keep directory listings bounded.
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np


def _hash_hex(block_hash: int) -> str:
    return struct.pack("<q", block_hash).hex()


def _hex_hash(hexstr: str) -> int:
    return struct.unpack("<q", bytes.fromhex(hexstr))[0]


class FSKVBackend:
    """KV blocks as files; directory = index; async-capable via a thread pool
    (the reference uses a NUMA-aware pool of 64 threads/GPU — here sized by arg)."""

    def __init__(self, shared_storage_path: str, threads: int = 4) -> None:
        self.root = shared_storage_path
        os.makedirs(self.root, exist_ok=True)
        self._pool = ThreadPoolExecutor(max_workers=threads, thread_name_prefix="fskv")
        self._lock = threading.Lock()
        self.puts = 0
        self.gets = 0
        self.misses = 0

    # ------------------------------------------------------------------ paths
    def _path(self, block_hash: int) -> str:
        h = _hash_hex(block_hash)
        return os.path.join(self.root, h[:2], h + ".kvblock")

    # ------------------------------------------------------------------ ops
    def put(self, block_hash: int, array: np.ndarray) -> None:
        """Atomic write; concurrent identical writes are harmless (same content)."""
        path = self._path(block_hash)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        meta = {"shape": list(array.shape), "dtype": str(array.dtype)}
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                header = json.dumps(meta).encode()
                f.write(struct.pack("<I", len(header)))
                f.write(header)
                f.write(array.tobytes())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.puts += 1

    def put_async(self, block_hash: int, array: np.ndarray):
        return self._pool.submit(self.put, block_hash, array)

    def get(self, block_hash: int) -> Optional[np.ndarray]:
        path = self._path(block_hash)
        try:
            with open(path, "rb") as f:
                (hlen,) = struct.unpack("<I", f.read(4))
                meta = json.loads(f.read(hlen))
                raw = f.read()
            os.utime(path)  # refresh LRU mtime for the external evictor
        except (OSError, ValueError, json.JSONDecodeError):
            self.misses += 1
            return None
        import ml_dtypes  # registered numpy extension dtypes (bfloat16)

        dtype = np.dtype(getattr(ml_dtypes, meta["dtype"], None) or meta["dtype"])
        self.gets += 1
        return np.frombuffer(raw, dtype=dtype).reshape(meta["shape"])

    def contains(self, block_hash: int) -> bool:
        return os.path.exists(self._path(block_hash))

    def remove(self, block_hash: int) -> bool:
        try:
            os.unlink(self._path(block_hash))
            return True
        except OSError:
            return False

    # ------------------------------------------------------------------ index
    def scan(self) -> Iterator[int]:
        """Directory walk = the index (kv-offloader.md 'directory=index')."""
        for shard in sorted(os.listdir(self.root)):
            sdir = os.path.join(self.root, shard)
            if not os.path.isdir(sdir):
                continue
            for name in os.listdir(sdir):
                if name.endswith(".kvblock"):
                    yield _hex_hash(name[: -len(".kvblock")])

    def total_bytes(self) -> int:
        total = 0
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                if f.endswith(".kvblock"):
                    total += os.path.getsize(os.path.join(dirpath, f))
        return total

    # ------------------------------------------------------------------ evictor
    def evict_to_bytes(self, max_bytes: int) -> list[int]:
        """External-evictor pass (PVC Evictor analogue): drop oldest-mtime blocks
        until total size ≤ max_bytes. Returns evicted hashes (for KV events)."""
        entries = []
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                if f.endswith(".kvblock"):
                    p = os.path.join(dirpath, f)
                    try:
                        st = os.stat(p)
                    except OSError:
                        continue
                    entries.append((st.st_mtime, st.st_size, p, f))
        total = sum(e[1] for e in entries)
        evicted: list[int] = []
        for mtime, size, path, name in sorted(entries):
            if total <= max_bytes:
                break
            try:
                os.unlink(path)
                total -= size
                evicted.append(_hex_hash(name[: -len(".kvblock")]))
            except OSError:
                pass
        return evicted

    def close(self) -> None:
        self._pool.shutdown(wait=True)
