"""Test fixtures: fake model server, workload generators (SURVEY.md §4)."""
