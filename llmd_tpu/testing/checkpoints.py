"""Generate genuine HF-format checkpoints locally (zero-egress test fixtures).

The image has no network, so no published checkpoint can be downloaded — but the
HF *format* (config.json + safetensors [+ sharded index] + tokenizer files) and
the HF *reference implementation* (transformers on torch CPU) are both available.
These fixtures build real ``save_pretrained`` checkpoints for each supported
architecture family so ``llmd_tpu.models.hf_loader`` and the engine can be
validated for logits parity against the HF forward — the exact validation a real
downloaded checkpoint would get (the loader path is identical; only the weight
values differ).

Also used by ``tools/make_checkpoint.py`` to materialise serving-scale
checkpoints (e.g. a Llama-3.2-1B-shaped model) for bench runs through the full
HF-load path.
"""

from __future__ import annotations

import os
from typing import Optional

_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "llm-d is a kubernetes-native distributed inference serving stack",
    "tensor parallel expert parallel data parallel sequence parallel",
    "paged attention continuous batching chunked prefill speculative",
    "prefill decode disaggregation kv cache transfer routing scheduler",
    "0123456789 !?.,;:()[]{}<>@#$%^&*-_=+ abcdefghijklmnopqrstuvwxyz",
]


def make_hf_tokenizer(out_dir: str, vocab_size: int = 384) -> int:
    """Train + save a real byte-level BPE HF tokenizer; returns its vocab size."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<eos>", "<bos>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(_CORPUS * 4, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok, eos_token="<eos>", bos_token="<bos>"
    )
    fast.save_pretrained(out_dir)
    return len(fast)


def make_hf_checkpoint(
    out_dir: str,
    family: str = "llama",
    *,
    vocab_size: int = 384,
    hidden_size: int = 64,
    intermediate_size: int = 128,
    num_layers: int = 2,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    head_dim: Optional[int] = None,
    tie_embeddings: bool = True,
    rope_theta: float = 10000.0,
    max_position: int = 512,
    max_shard_size: Optional[str] = None,
    seed: int = 0,
    with_tokenizer: bool = True,
    torch_dtype: str = "float32",
    attention_bias: bool = False,
) -> str:
    """Build + save an HF checkpoint of the given family; returns ``out_dir``.

    ``max_shard_size`` (e.g. "50KB") forces a sharded model.safetensors.index.json
    checkpoint, exercising the loader's multi-shard path.
    """
    import torch
    import transformers

    torch.manual_seed(seed)
    common = dict(
        vocab_size=vocab_size,
        hidden_size=hidden_size,
        intermediate_size=intermediate_size,
        num_hidden_layers=num_layers,
        num_attention_heads=num_heads,
        num_key_value_heads=num_kv_heads,
        max_position_embeddings=max_position,
        rms_norm_eps=1e-6,
        rope_theta=rope_theta,
        tie_word_embeddings=tie_embeddings,
    )
    if family == "llama":
        cfg = transformers.LlamaConfig(
            **common, head_dim=head_dim, attention_bias=attention_bias
        )
        model = transformers.LlamaForCausalLM(cfg)
    elif family == "qwen2":
        cfg = transformers.Qwen2Config(**common)
        model = transformers.Qwen2ForCausalLM(cfg)
    elif family == "qwen3":
        cfg = transformers.Qwen3Config(
            **common, head_dim=head_dim or hidden_size // num_heads
        )
        model = transformers.Qwen3ForCausalLM(cfg)
    else:
        raise ValueError(f"unknown family {family!r}")
    model = model.to(getattr(torch, torch_dtype))
    os.makedirs(out_dir, exist_ok=True)
    kwargs = dict(safe_serialization=True)
    if max_shard_size is not None:
        kwargs["max_shard_size"] = max_shard_size
    model.save_pretrained(out_dir, **kwargs)
    if with_tokenizer:
        make_hf_tokenizer(out_dir, vocab_size=min(vocab_size, 384))
    return out_dir
