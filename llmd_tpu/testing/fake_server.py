"""Fake model server: the control plane's hardware-free test fixture.

Implements the full model-server contract the router depends on (SURVEY.md §4):

- OpenAI HTTP API: ``/v1/completions``, ``/v1/chat/completions`` (+streaming)
- render/tokenize endpoints: ``/v1/completions/render`` (kv-indexer.md:104-113)
- Prometheus ``/metrics`` with the vLLM-compatible names (model-servers.md:38-52)
- ``/health`` liveness/readiness (model-servers.md:81-86)
- ZMQ KV-event publishing with a simulated paged prefix cache (kv-indexer.md:59-87)

Timing model: prefill cost ∝ uncached prompt tokens, decode cost ∝ output tokens, so
prefix-cache-aware routing measurably beats round-robin in tests — mirroring the
reference's optimized-baseline benchmark design (BASELINE.md row 7).
"""

from __future__ import annotations

import asyncio
import json
import random
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

from aiohttp import web

import zmq
import zmq.asyncio

from llmd_tpu.core.kv_events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    block_keys_for_tokens,
    encode_event_batch,
    kv_topic,
)
from llmd_tpu.core.request import flatten_messages


def fake_tokenize(text: str) -> list[int]:
    """Deterministic byte-level tokenizer shared by fixture and router tests."""
    return list(text.encode("utf-8"))


@dataclass
class FakeServerConfig:
    model: str = "fake/model"
    block_size: int = 16
    num_blocks: int = 512
    prefill_us_per_token: float = 50.0  # uncached prompt tokens
    decode_us_per_token: float = 500.0
    kv_pull_us_per_block: float = 200.0  # P→D remote-prefill transfer cost
    max_running: int = 8
    kv_events_port: Optional[int] = None  # bind tcp://*:port when set (pod-discovery mode)
    role: str = "both"  # prefill | decode | both
    lora_adapters: list[str] = field(default_factory=list)


@dataclass
class FaultConfig:
    """Programmable fault injection (resilience tests, tools/chaos_check.py).

    Faults target the generation endpoints; /metrics and /health have their
    own flags. The RNG is seeded so chaos runs replay deterministically."""

    error_rate: float = 0.0  # fraction of generate requests → error_status
    error_status: int = 503
    connect_refuse: bool = False  # kill the connection instead of answering
    latency_s: float = 0.0  # added latency before each generate request
    # slow-replica injection (SLO harness): stretch the timing model instead
    # of failing outright — the router sees a healthy-but-slow endpoint
    first_byte_delay_s: float = 0.0  # added to the prefill phase (TTFT)
    decode_delay_s: float = 0.0  # added per generated token (ITL)
    jitter_s: float = 0.0  # uniform [0, jitter] extra on each injected delay
    midstream_hangup_rate: float = 0.0  # streaming: cut after the first chunk
    flap_period_s: float = 0.0  # >0: alternate up/down on this period
    flap_duty: float = 0.5  # fraction of each period the server is UP
    fail_metrics: bool = False  # /metrics answers 500 (scrape-error paths)
    fail_health: bool = False  # /health answers 503
    seed: int = 0


class FakeModelServer:
    def __init__(self, cfg: FakeServerConfig, host: str = "127.0.0.1", port: int = 0):
        self.cfg = cfg
        self.host, self.port = host, port
        self.running = 0
        self.queued = 0
        self.request_count = 0
        # Simulated paged prefix cache: block_hash → last-use (LRU).
        self.blocks: OrderedDict[int, float] = OrderedDict()
        self._zctx = None
        self._pub = None
        self._seq = 0
        self._runner: Optional[web.AppRunner] = None
        self._admit = asyncio.Semaphore(cfg.max_running)
        self.received: list[dict] = []  # request log for assertions
        # fault injection (resilience/chaos tests): mutate via set_faults()
        self.faults = FaultConfig()
        self._fault_rng = random.Random(self.faults.seed)
        self._flap_t0 = time.monotonic()
        self.fault_counts = {"errors": 0, "refused": 0, "midstream": 0}
        self.draining = False  # POST /drain mirrors the engine server
        # cross-engine prefix-pull simulation (docs/kv-plane.md)
        self.pulls_completed = 0
        self.pulled_blocks = 0
        # P/D disaggregation (docs/pd-disaggregation.md): count of requests
        # that adopted a remote prefiller's KV instead of prefilling locally
        self.remote_pulls = 0
        # per-request phase timelines in the flight-record to_dict() shape,
        # so gates can fold them with obs.attribution.build_ledger verbatim
        self.request_records: list[dict] = []

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/embeddings", self._embeddings)
        app.router.add_post("/v1/completions/render", self._render)
        app.router.add_post("/v1/chat/completions/render", self._render)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        app.router.add_post("/drain", self._drain)
        app.router.add_get("/v1/models", self._models)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        if self.cfg.kv_events_port is not None:
            self._zctx = zmq.asyncio.Context()
            self._pub = self._zctx.socket(zmq.PUB)
            if self.cfg.kv_events_port == 0:
                self.cfg.kv_events_port = self._pub.bind_to_random_port("tcp://127.0.0.1")
            else:
                self._pub.bind(f"tcp://127.0.0.1:{self.cfg.kv_events_port}")

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        if self._pub is not None:
            self._pub.close(0)
            self._zctx.term()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- KV cache simulation ----------------------------------------------
    async def _publish(self, events) -> None:
        if self._pub is None:
            return
        self._seq += 1
        topic = kv_topic(self.address, self.cfg.model).encode()
        await self._pub.send_multipart([topic, encode_event_batch(events, self._seq)])

    async def _touch_blocks(self, token_ids: list[int], lora: Optional[str]) -> int:
        """Insert/refresh blocks for tokens; publish events; return cached-prefix len."""
        keys = block_keys_for_tokens(token_ids, self.cfg.block_size, lora)
        cached = 0
        for k in keys:
            if k in self.blocks:
                cached += 1
            else:
                break
        now = time.monotonic()
        stored, removed = [], []
        parent = keys[cached - 1] if cached else None
        new_keys = keys[cached:]
        for k in keys:
            self.blocks[k] = now
            self.blocks.move_to_end(k)
        while len(self.blocks) > self.cfg.num_blocks:
            old, _ = self.blocks.popitem(last=False)
            removed.append(old)
        if new_keys:
            chunk = token_ids[cached * self.cfg.block_size : len(keys) * self.cfg.block_size]
            stored.append(BlockStored(
                block_hashes=new_keys, parent_block_hash=parent, token_ids=chunk,
                block_size=self.cfg.block_size, lora_id=lora,
            ))
        events = stored + ([BlockRemoved(block_hashes=removed)] if removed else [])
        if events:
            await self._publish(events)
        return cached * self.cfg.block_size

    async def clear_cache(self) -> None:
        self.blocks.clear()
        await self._publish([AllBlocksCleared()])

    async def _simulate_prefix_pull(self, token_ids: list[int],
                                    lora: Optional[str],
                                    hashes: list) -> int:
        """Adopt router-stamped pulled blocks ahead of admission. Only the
        stamped hashes that agree with this prompt's own chain are adopted
        (hash-chain verification, like ``inject_into_engine``); adopted
        blocks then count as cached in ``_touch_blocks`` and are published
        so the router index learns this pod now holds them."""
        keys = block_keys_for_tokens(token_ids, self.cfg.block_size, lora)
        n = 0
        for k, h in zip(keys, hashes):
            if int(h) != k:
                break
            n += 1
        if not n:
            return 0
        cached = 0
        for k in keys[:n]:
            if k in self.blocks:
                cached += 1
            else:
                break
        now = time.monotonic()
        for k in keys[:n]:
            self.blocks[k] = now
            self.blocks.move_to_end(k)
        new_keys = keys[cached:n]
        if new_keys:
            await self._publish([BlockStored(
                block_hashes=new_keys,
                parent_block_hash=keys[cached - 1] if cached else None,
                token_ids=token_ids[cached * self.cfg.block_size : n * self.cfg.block_size],
                block_size=self.cfg.block_size, lora_id=lora,
            )])
        self.pulls_completed += 1
        self.pulled_blocks += n
        return n

    # -- fault injection ---------------------------------------------------
    def set_faults(self, **kw) -> None:
        """Update fault knobs at runtime (``set_faults(error_rate=0.2)``);
        passing ``seed`` reseeds the RNG, ``flap_period_s`` restarts the
        flap schedule from 'up'."""
        for k, v in kw.items():
            if not hasattr(self.faults, k):
                raise AttributeError(f"unknown fault knob {k!r}")
            setattr(self.faults, k, v)
        if "seed" in kw:
            self._fault_rng = random.Random(kw["seed"])
        if "flap_period_s" in kw:
            self._flap_t0 = time.monotonic()

    def _injected_delay(self, base_s: float) -> float:
        """A latency-knob value plus its jitter draw (seeded RNG, so runs
        replay). Jitter only applies where a base delay is configured."""
        if base_s <= 0:
            return 0.0
        return base_s + self._fault_rng.uniform(0.0, self.faults.jitter_s)

    def _flap_down(self) -> bool:
        f = self.faults
        if f.flap_period_s <= 0:
            return False
        phase = ((time.monotonic() - self._flap_t0) % f.flap_period_s) / f.flap_period_s
        return phase >= f.flap_duty

    def _refuse(self, request: web.Request):
        """Kill the connection without an HTTP response: the client sees a
        reset/disconnect, i.e. a connect-class (retryable) failure."""
        self.fault_counts["refused"] += 1
        if request.transport is not None:
            request.transport.close()
        raise ConnectionResetError("fault: connection refused")

    async def _maybe_fault(self, request: web.Request) -> Optional[web.Response]:
        """Evaluate the fault schedule for one generate request. Returns an
        error response, raises (connect-refuse), or returns None (healthy)."""
        f = self.faults
        if f.latency_s > 0:
            await asyncio.sleep(f.latency_s)
        if f.connect_refuse:
            self._refuse(request)
        if self._flap_down() or (
                f.error_rate > 0 and self._fault_rng.random() < f.error_rate):
            self.fault_counts["errors"] += 1
            return web.json_response({"error": {"message": "fault injected"}},
                                     status=f.error_status)
        return None

    # -- handlers ----------------------------------------------------------
    def _close_record(self, rid: str, events: list[dict], t_open: float,
                      status: str = "finished") -> None:
        """Retire one request's phase timeline. ``latency_ms`` is the retired
        stamp itself, so build_ledger's intervals partition the wall exactly."""
        events.append({"event": "retired",
                       "t_ms": round((time.monotonic() - t_open) * 1e3, 3)})
        self.request_records.append({
            "request_id": rid, "model": self.cfg.model, "status": status,
            "latency_ms": events[-1]["t_ms"], "events": events})
        if len(self.request_records) > 4096:
            del self.request_records[: len(self.request_records) - 4096]

    async def _serve_generation(self, request: web.Request, prompt: str, body: dict, chat: bool):
        lora = body.get("model") if body.get("model") in self.cfg.lora_adapters else None
        token_ids = fake_tokenize(prompt)
        max_tokens = int(body.get("max_tokens", 16))
        stream = bool(body.get("stream", False))
        # kv_transfer_params flow for P/D (disaggregation/README.md:104-131).
        kv_params = body.get("kv_transfer_params") or {}
        self.request_count += 1
        self.received.append({"prompt": prompt, "body": body, "t": time.monotonic()})
        if self.cfg.role == "prefill" and not kv_params.get("do_remote_decode"):
            # prefill-only replica: decode-phase work must carry the P/D
            # handshake. A client error, never a 5xx — misrouted traffic
            # should bounce to the sender, not trip breakers/retries.
            return web.json_response(
                {"error": {"message": "prefill-only replica refuses decode "
                                      "work (missing do_remote_decode)",
                           "type": "invalid_request_error"}}, status=400)
        if self.draining:
            return web.json_response({"error": {"message": "draining"}},
                                     status=503, headers={"Retry-After": "1"})
        faulted = await self._maybe_fault(request)
        if faulted is not None:
            return faulted
        # decided up front so one seeded RNG draw covers the whole stream
        hangup = (stream and self.faults.midstream_hangup_rate > 0
                  and self._fault_rng.random() < self.faults.midstream_hangup_rate)

        t_open = time.monotonic()
        events: list[dict] = []

        def ev(name: str) -> None:
            events.append({"event": name,
                           "t_ms": round((time.monotonic() - t_open) * 1e3, 3)})

        rid = f"cmpl-{uuid.uuid4().hex[:12]}"
        remote_pull = bool(kv_params.get("do_remote_prefill")
                           and kv_params.get("remote_request_id"))
        if remote_pull:
            # P/D split decode side: price the P→D transfer per block, then
            # adopt the prompt's whole chain — local prefill is skipped, and
            # the phase ledger shows kv_pull where prefill would have been
            keys = block_keys_for_tokens(token_ids, self.cfg.block_size, lora)
            await asyncio.sleep(
                max(1, len(keys)) * self.cfg.kv_pull_us_per_block / 1e6)
            now = time.monotonic()
            for k in keys:
                self.blocks[k] = now
                self.blocks.move_to_end(k)
            self.remote_pulls += 1
            ev("kv_pull")
        else:
            ev("arrival")

        self.queued += 1
        async with self._admit:  # FIFO-ish admission, no busy-wait
            self.queued -= 1
            self.running += 1
            try:
                if kv_params.get("do_prefix_pull") and kv_params.get("block_hashes"):
                    await self._simulate_prefix_pull(
                        token_ids, lora, kv_params["block_hashes"])
                cached = await self._touch_blocks(token_ids, lora)
                if remote_pull:
                    cached = len(token_ids)  # full KV arrived from P
                uncached = max(0, len(token_ids) - cached)
                prefill_s = (uncached * self.cfg.prefill_us_per_token / 1e6
                             + self._injected_delay(self.faults.first_byte_delay_s))
                tpot_s = (self.cfg.decode_us_per_token / 1e6
                          + self._injected_delay(self.faults.decode_delay_s))
                ev("admitted")
                model = body.get("model", self.cfg.model)
                usage = {
                    "prompt_tokens": len(token_ids), "completion_tokens": max_tokens,
                    "total_tokens": len(token_ids) + max_tokens, "cached_tokens": cached,
                }

                if stream:
                    resp = web.StreamResponse(headers={"Content-Type": "text/event-stream"})
                    await resp.prepare(request)
                    if not remote_pull:
                        ev("prefill_start")
                    await asyncio.sleep(prefill_s)
                    if not remote_pull:
                        ev("prefill_end")
                    for i in range(max_tokens):
                        if hangup and i == 1:
                            # mid-stream hangup AFTER the first chunk: the
                            # client holds partial output, so the router must
                            # NOT retry — exactly the case under test
                            self.fault_counts["midstream"] += 1
                            self._refuse(request)
                        await asyncio.sleep(tpot_s)
                        if i == 0:
                            ev("first_token")
                        chunk = {
                            "id": rid, "model": model, "created": int(time.time()),
                            "object": "chat.completion.chunk" if chat else "text_completion",
                            "choices": [
                                {"index": 0, "delta": {"content": f"t{i} "}}
                                if chat else {"index": 0, "text": f"t{i} "}
                            ],
                        }
                        if i == max_tokens - 1:
                            chunk["usage"] = usage
                        await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    ev("decode")
                    await resp.write(b"data: [DONE]\n\n")
                    await resp.write_eof()
                    self._close_record(rid, events, t_open)
                    return resp

                if not remote_pull:
                    ev("prefill_start")
                await asyncio.sleep(prefill_s)
                if not remote_pull:
                    ev("prefill_end")
                await asyncio.sleep(tpot_s)
                ev("first_token")
                if max_tokens > 1:
                    await asyncio.sleep((max_tokens - 1) * tpot_s)
                ev("decode")
                text = f"echo({len(token_ids)}t,{max_tokens}o)"
                out: dict = {
                    "id": rid, "object": "chat.completion" if chat else "text_completion",
                    "model": model, "created": int(time.time()), "usage": usage,
                    "choices": [
                        {"index": 0, "message": {"role": "assistant", "content": text}}
                        if chat else {"index": 0, "text": text, "finish_reason": "length"}
                    ],
                }
                if kv_params.get("do_remote_decode"):
                    out["kv_transfer_params"] = {
                        "remote_host": self.host, "remote_port": self.port,
                        "remote_request_id": rid, "remote_block_ids": list(range(len(token_ids) // self.cfg.block_size)),
                    }
                self._close_record(rid, events, t_open)
                return web.json_response(out)
            finally:
                self.running -= 1

    async def _completions(self, request: web.Request):
        body = await request.json()
        return await self._serve_generation(request, str(body.get("prompt", "")), body, chat=False)

    async def _chat(self, request: web.Request):
        body = await request.json()
        prompt = flatten_messages(body.get("messages", []))
        return await self._serve_generation(request, prompt, body, chat=True)

    async def _embeddings(self, request: web.Request) -> web.Response:
        import hashlib

        body = await request.json()
        inp = body.get("input", "")
        items = [inp] if isinstance(inp, str) else list(inp)
        self.request_count += 1
        data = []
        for i, item in enumerate(items):
            # deterministic pseudo-embedding from the content hash
            h = hashlib.sha256(str(item).encode()).digest()
            vec = [((b / 255.0) * 2 - 1) for b in h[:16]]
            data.append({"object": "embedding", "index": i, "embedding": vec})
        ntok = sum(len(fake_tokenize(str(it))) for it in items)
        return web.json_response({
            "object": "list", "model": body.get("model", self.cfg.model), "data": data,
            "usage": {"prompt_tokens": ntok, "total_tokens": ntok},
        })

    async def _render(self, request: web.Request) -> web.Response:
        body = await request.json()
        if "messages" in body:
            prompt = flatten_messages(body.get("messages", []))
        else:
            prompt = str(body.get("prompt", ""))
        return web.json_response({"prompt_token_ids": fake_tokenize(prompt)})

    async def _metrics(self, request: web.Request) -> web.Response:
        if self.faults.fail_metrics:
            return web.Response(status=500, text="fault: metrics down")
        util = min(1.0, len(self.blocks) / self.cfg.num_blocks)
        lines = [
            f"vllm:num_requests_waiting {self.queued}",
            f"vllm:num_requests_running {self.running}",
            f"vllm:kv_cache_usage_perc {util:.6f}",
            f'vllm:cache_config_info{{block_size="{self.cfg.block_size}",num_gpu_blocks="{self.cfg.num_blocks}"}} 1',
        ]
        if self.cfg.role == "decode":
            # decode replicas advertise the kv-transfer side channel the
            # prefiller pushes into (disaggregation/README.md:104-131)
            lines.append(
                f'vllm:kv_transfer_config_info{{kv_role="kv_consumer",'
                f'side_channel_host="{self.host}",'
                f'side_channel_port="{self.port}"}} 1')
        if self.cfg.lora_adapters:
            running = ",".join(self.cfg.lora_adapters[:1])
            lines.append(
                f'vllm:lora_requests_info{{max_lora="4",running_lora_adapters="{running}",'
                f'waiting_lora_adapters=""}} {time.time():.3f}'
            )
        return web.Response(text="\n".join(lines) + "\n")

    async def _health(self, request: web.Request) -> web.Response:
        if self.faults.fail_health:
            return web.json_response({"status": "unhealthy"}, status=503)
        if self.draining:
            return web.json_response(
                {"status": "draining", "inflight": self.running}, status=503)
        return web.json_response({"status": "ok", "role": self.cfg.role})

    async def _drain(self, request: web.Request) -> web.Response:
        """Engine-server /drain contract: stop admissions, wait (bounded) for
        in-flight generations to finish. ``{"enable": false}`` re-opens."""
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if body.get("enable") is False:
            self.draining = False
            return web.json_response({"status": "ok", "draining": False})
        self.draining = True
        try:
            timeout_s = float(request.query.get("timeout_s", 10.0))
        except ValueError:
            return web.json_response(
                {"error": {"message": "timeout_s must be a number"}}, status=400)
        t0 = time.monotonic()
        while self.running and time.monotonic() - t0 < timeout_s:
            await asyncio.sleep(0.01)
        drained = self.running == 0
        return web.json_response(
            {"status": "drained" if drained else "timeout",
             "inflight": self.running},
            status=200 if drained else 504)

    async def _models(self, request: web.Request) -> web.Response:
        data = [{"id": self.cfg.model, "object": "model"}]
        data += [{"id": a, "object": "model", "parent": self.cfg.model} for a in self.cfg.lora_adapters]
        return web.json_response({"object": "list", "data": data})


def main() -> int:
    """CLI: run one fake replica as a standalone process — the pool
    controller's ProcessReplicaLauncher target for hardware-free runs."""
    import argparse

    ap = argparse.ArgumentParser(description="standalone FakeModelServer")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--model", default="fake/model")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=512)
    ap.add_argument("--max-running", type=int, default=8)
    ap.add_argument("--prefill-us-per-token", type=float, default=50.0)
    ap.add_argument("--decode-us-per-token", type=float, default=500.0)
    ap.add_argument("--kv-pull-us-per-block", type=float, default=200.0)
    ap.add_argument("--role", default="both",
                    choices=["prefill", "decode", "both"])
    args = ap.parse_args()

    cfg = FakeServerConfig(
        model=args.model, block_size=args.block_size,
        num_blocks=args.num_blocks, max_running=args.max_running,
        prefill_us_per_token=args.prefill_us_per_token,
        decode_us_per_token=args.decode_us_per_token,
        kv_pull_us_per_block=args.kv_pull_us_per_block, role=args.role)
    server = FakeModelServer(cfg, host=args.host, port=args.port)

    async def run() -> None:
        await server.start()
        print(f"fake model server on http://{server.address}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
