"""Minimal Redis/Valkey-wire (RESP2) server — the in-repo stand-in for the
external index store (kv/index_backends.ExternalKVBlockIndex), playing the
role Valkey plays for the reference's Redis index backend
(kv-indexer.md:64-101). Command subset the index layout needs: PING, HSET,
HGET, HGETALL, HDEL, DEL, SADD, SREM, SMEMBERS, DBSIZE, FLUSHALL.

Thread-per-connection over blocking sockets (the house fixture style —
testing/fake_server.py is asyncio because it speaks HTTP; RESP is simpler).
No eviction: a real Valkey brings its own maxmemory policy.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional


class RespStoreServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host, self.port = host, port
        self._hashes: dict[bytes, dict[bytes, bytes]] = {}
        self._sets: dict[bytes, set[bytes]] = {}
        self._lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._srv = socket.create_server((self.host, self.port))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="resp-store").start()

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:  # wake a blocked accept() (see kv/remote_store.py stop())
                with socket.create_connection(
                        ("127.0.0.1" if self.host in ("0.0.0.0", "::")
                         else self.host, self.port), timeout=0.2):
                    pass
            except OSError:
                pass
            self._srv.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    # -- wire --------------------------------------------------------------
    def _serve(self, conn: socket.socket) -> None:
        buf = b""

        def read_line() -> Optional[bytes]:
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf += chunk
            line, buf = buf.split(b"\r\n", 1)
            return line

        def read_exact(n: int) -> Optional[bytes]:
            nonlocal buf
            while len(buf) < n + 2:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf += chunk
            data, buf = buf[:n], buf[n + 2:]
            return data

        try:
            with conn:
                while not self._stop.is_set():
                    line = read_line()
                    if line is None:
                        return
                    if not line.startswith(b"*"):
                        conn.sendall(b"-ERR protocol\r\n")
                        return
                    parts = []
                    for _ in range(int(line[1:])):
                        hdr = read_line()
                        if hdr is None or not hdr.startswith(b"$"):
                            return
                        val = read_exact(int(hdr[1:]))
                        if val is None:
                            return
                        parts.append(val)
                    conn.sendall(self._dispatch(parts))
        except (OSError, ValueError):
            pass

    # -- commands ----------------------------------------------------------
    @staticmethod
    def _bulk(v: Optional[bytes]) -> bytes:
        return b"$-1\r\n" if v is None else b"$%d\r\n%s\r\n" % (len(v), v)

    def _dispatch(self, parts: list[bytes]) -> bytes:
        cmd, args = parts[0].upper(), parts[1:]
        with self._lock:
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"HSET":
                h = self._hashes.setdefault(args[0], {})
                added = 0
                for i in range(1, len(args), 2):
                    added += args[i] not in h
                    h[args[i]] = args[i + 1]
                return b":%d\r\n" % added
            if cmd == b"HGET":
                return self._bulk(self._hashes.get(args[0], {}).get(args[1]))
            if cmd == b"HGETALL":
                h = self._hashes.get(args[0], {})
                out = b"*%d\r\n" % (2 * len(h))
                for k, v in h.items():
                    out += self._bulk(k) + self._bulk(v)
                return out
            if cmd == b"HDEL":
                h = self._hashes.get(args[0], {})
                n = 0
                for f in args[1:]:
                    n += h.pop(f, None) is not None
                if not h:
                    self._hashes.pop(args[0], None)
                return b":%d\r\n" % n
            if cmd == b"DEL":
                n = 0
                for k in args:
                    n += (self._hashes.pop(k, None) is not None
                          or self._sets.pop(k, None) is not None)
                return b":%d\r\n" % n
            if cmd == b"SADD":
                s = self._sets.setdefault(args[0], set())
                n = len(args[1:]) - len(s.intersection(args[1:]))
                s.update(args[1:])
                return b":%d\r\n" % n
            if cmd == b"SREM":
                s = self._sets.get(args[0], set())
                n = len(s.intersection(args[1:]))
                s.difference_update(args[1:])
                if not s:
                    self._sets.pop(args[0], None)
                return b":%d\r\n" % n
            if cmd == b"SMEMBERS":
                s = sorted(self._sets.get(args[0], set()))
                return b"*%d\r\n" % len(s) + b"".join(self._bulk(m) for m in s)
            if cmd == b"DBSIZE":
                return b":%d\r\n" % (len(self._hashes) + len(self._sets))
            if cmd == b"FLUSHALL":
                self._hashes.clear()
                self._sets.clear()
                return b"+OK\r\n"
            return b"-ERR unknown command '%s'\r\n" % cmd


def main() -> None:
    """CLI: python -m llmd_tpu.testing.resp_server --port 6379"""
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6379)
    args = ap.parse_args()
    srv = RespStoreServer(args.host, args.port)
    srv.start()
    print(f"llmd-tpu RESP store on {srv.host}:{srv.port}", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
