from llmd_tpu.benchmark.harness import main

main()
