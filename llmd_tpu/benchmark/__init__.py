"""Benchmark harness: workload profiles, load generation, RR-vs-scheduler
comparison (the reference's `llmdbenchmark` / inference-perf role)."""

from llmd_tpu.benchmark.harness import (  # noqa: F401
    LoadResult,
    WorkloadSpec,
    build_requests,
    compare_targets,
    run_load,
)
