"""Benchmark harness: workload profiles → load generation → JSON reports.

The reference treats benchmarks as reviewed artifacts: the `llmdbenchmark` CLI
deploys an inference-perf harness with per-guide workload profiles, runs rate
ladders, and checks in the analyzed results
(/root/reference/helpers/benchmark.md, guides/pd-disaggregation/
README.md:229-310, guides/optimized-baseline/README.md — whose first headline
is the scheduler beating round-robin +130% on a shared-prefix workload). This
module is that harness for the TPU stack:

- **workload profiles**: ``shared-prefix`` (N prefix groups × M requests — the
  prefix-cache-aware-routing workload), ``random`` (sanity_random analogue),
  ``long-context`` (few long prompts, chunked-prefill stressor).
- **arrival models**: closed-loop concurrency or open-loop Poisson rates, and
  rate ladders sweeping QPS (the reference's 3→60 QPS sweeps).
- **metrics**: output tok/s, TTFT (streaming first-chunk) mean/p50/p90, e2e
  mean/p90, error counts — the inference-perf summary fields.
- **comparison mode**: the same workload against multiple targets (e.g. a
  round-robin proxy vs the EPP router) in one report —
  ``tools/run_sched_comparison.py`` produces the RR-vs-scheduler artifact.

CLI: python -m llmd_tpu.benchmark --target host:port --workload shared-prefix
         [--requests 64] [--concurrency 8] [--rate-ladder 2,4,8] [--stream]
         [--out report.json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time
from dataclasses import dataclass, field
from typing import Optional

import aiohttp


@dataclass
class WorkloadSpec:
    kind: str = "shared-prefix"  # shared-prefix | random | long-context
    num_requests: int = 64
    max_tokens: int = 32
    prompt_words: int = 120  # ~input length in words
    prefix_groups: int = 4  # shared-prefix: distinct prefix groups
    prefix_words: int = 100  # shared-prefix: words shared within a group
    long_prompt_words: int = 2000  # long-context profile
    model: str = "fake/model"
    seed: int = 0

    def describe(self) -> dict:
        return {k: v for k, v in self.__dict__.items()}


_WORDS = ("the of to and in that for with as on at by from up out if about "
          "into over after tokens routing prefill decode cache expert shard "
          "mesh page block batch stream latency throughput schedule").split()


def _words(rng: random.Random, n: int) -> str:
    return " ".join(rng.choice(_WORDS) for _ in range(n))


def build_requests(spec: WorkloadSpec) -> list[dict]:
    """Materialise the workload as OpenAI /v1/completions bodies."""
    rng = random.Random(spec.seed)
    out: list[dict] = []
    if spec.kind == "shared-prefix":
        prefixes = [_words(rng, spec.prefix_words) for _ in range(spec.prefix_groups)]
        for i in range(spec.num_requests):
            p = prefixes[i % spec.prefix_groups]
            suffix = _words(rng, max(1, spec.prompt_words - spec.prefix_words))
            out.append({"model": spec.model, "prompt": f"{p} {suffix}",
                        "max_tokens": spec.max_tokens})
        # realistic arrival order: groups interleave arbitrarily (a strict
        # rotation would alias with a round-robin balancer's rotation and make
        # RR accidentally sticky)
        rng.shuffle(out)
    elif spec.kind == "random":
        for _ in range(spec.num_requests):
            out.append({"model": spec.model,
                        "prompt": _words(rng, spec.prompt_words),
                        "max_tokens": spec.max_tokens})
    elif spec.kind == "long-context":
        for _ in range(spec.num_requests):
            out.append({"model": spec.model,
                        "prompt": _words(rng, spec.long_prompt_words),
                        "max_tokens": spec.max_tokens})
    else:
        raise ValueError(f"unknown workload kind {spec.kind!r}")
    return out


@dataclass
class LoadResult:
    wall_s: float = 0.0
    ttfts: list[float] = field(default_factory=list)
    e2es: list[float] = field(default_factory=list)
    out_tokens: int = 0
    errors: int = 0

    @staticmethod
    def _pct(xs: list[float], q: float) -> Optional[float]:
        if not xs:
            return None
        ys = sorted(xs)
        return ys[min(len(ys) - 1, int(q * len(ys)))]

    def summary(self) -> dict:
        n = len(self.e2es)
        return {
            "requests": n,
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "out_tok_per_s": round(self.out_tokens / self.wall_s, 1) if self.wall_s else 0,
            "req_per_s": round(n / self.wall_s, 2) if self.wall_s else 0,
            "ttft_mean_ms": round(sum(self.ttfts) / len(self.ttfts) * 1e3, 1) if self.ttfts else None,
            "ttft_p50_ms": round(self._pct(self.ttfts, 0.5) * 1e3, 1) if self.ttfts else None,
            "ttft_p90_ms": round(self._pct(self.ttfts, 0.9) * 1e3, 1) if self.ttfts else None,
            "e2e_mean_ms": round(sum(self.e2es) / n * 1e3, 1) if n else None,
            "e2e_p90_ms": round(self._pct(self.e2es, 0.9) * 1e3, 1) if n else None,
        }


async def _one(session: aiohttp.ClientSession, target: str, body: dict,
               stream: bool, result: LoadResult) -> None:
    t0 = time.monotonic()
    try:
        if stream:
            async with session.post(f"http://{target}/v1/completions",
                                    json={**body, "stream": True}) as resp:
                if resp.status != 200:
                    result.errors += 1
                    return
                first = None
                n_chunks = 0
                async for _chunk in resp.content.iter_any():
                    if first is None:
                        first = time.monotonic()
                    n_chunks += 1
                t1 = time.monotonic()
                if first is not None:
                    result.ttfts.append(first - t0)
                result.e2es.append(t1 - t0)
                result.out_tokens += body.get("max_tokens", 0)
        else:
            async with session.post(f"http://{target}/v1/completions",
                                    json=body) as resp:
                payload = await resp.json()
                t1 = time.monotonic()
                if resp.status != 200:
                    result.errors += 1
                    return
                result.e2es.append(t1 - t0)
                result.ttfts.append(t1 - t0)  # non-stream: TTFT == e2e
                result.out_tokens += payload.get("usage", {}).get(
                    "completion_tokens", body.get("max_tokens", 0))
    except (aiohttp.ClientError, asyncio.TimeoutError, json.JSONDecodeError, OSError):
        result.errors += 1


async def run_load(target: str, requests: list[dict], *,
                   concurrency: int = 8, rate_qps: Optional[float] = None,
                   stream: bool = False, seed: int = 0) -> LoadResult:
    """Closed-loop (``concurrency`` workers) or open-loop (Poisson ``rate_qps``)."""
    result = LoadResult()
    timeout = aiohttp.ClientTimeout(total=600)
    t0 = time.monotonic()
    async with aiohttp.ClientSession(timeout=timeout) as session:
        if rate_qps is None:
            queue: asyncio.Queue = asyncio.Queue()
            for body in requests:
                queue.put_nowait(body)

            async def worker() -> None:
                while True:
                    try:
                        body = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        return
                    await _one(session, target, body, stream, result)

            await asyncio.gather(*(worker() for _ in range(concurrency)))
        else:
            rng = random.Random(seed)
            tasks = []
            for body in requests:
                tasks.append(asyncio.get_running_loop().create_task(
                    _one(session, target, body, stream, result)))
                await asyncio.sleep(rng.expovariate(rate_qps))
            await asyncio.gather(*tasks)
    result.wall_s = time.monotonic() - t0
    return result


async def compare_targets(targets: dict[str, str], spec: WorkloadSpec, *,
                          concurrency: int = 8,
                          rate_qps: Optional[float] = None,
                          stream: bool = False) -> dict:
    """Same workload against each named target, sequentially (isolation)."""
    report: dict = {"workload": spec.describe(), "targets": {}}
    for name, addr in targets.items():
        res = await run_load(addr, build_requests(spec), concurrency=concurrency,
                             rate_qps=rate_qps, stream=stream)
        report["targets"][name] = res.summary()
    names = list(targets)
    if len(names) == 2:
        a, b = (report["targets"][n] for n in names)
        if a["out_tok_per_s"] and b["out_tok_per_s"]:
            report["delta"] = {
                f"{names[1]}_vs_{names[0]}_tput":
                    round(b["out_tok_per_s"] / a["out_tok_per_s"], 3),
            }
    return report


async def run_ladder(target: str, spec: WorkloadSpec, rates: list[float], *,
                     stream: bool = False) -> dict:
    """Open-loop rate ladder (the reference's QPS sweeps); one summary per rung."""
    rungs = []
    for rate in rates:
        res = await run_load(target, build_requests(spec), rate_qps=rate,
                             stream=stream)
        rungs.append({"rate_qps": rate, **res.summary()})
    return {"workload": spec.describe(), "ladder": rungs}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", required=True, help="host:port of router/engine")
    ap.add_argument("--workload", default="shared-prefix",
                    choices=["shared-prefix", "random", "long-context"])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rate-ladder", default=None,
                    help="comma-separated QPS rungs (open loop); default closed loop")
    ap.add_argument("--stream", action="store_true", help="measure streaming TTFT")
    ap.add_argument("--model", default="fake/model")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args()

    spec = WorkloadSpec(kind=args.workload, num_requests=args.requests,
                        max_tokens=args.max_tokens, model=args.model)
    if args.rate_ladder:
        rates = [float(r) for r in args.rate_ladder.split(",")]
        report = asyncio.run(run_ladder(args.target, spec, rates,
                                        stream=args.stream))
    else:
        report = asyncio.run(compare_targets({"target": args.target}, spec,
                                             concurrency=args.concurrency,
                                             stream=args.stream))
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
