"""Global KV plane: precise prefix-cache routing + cross-engine prefix pulls.

Unites the pieces that existed in isolation — the event-fed block index
(``llmd_tpu.kv.indexer``), the ZMQ event feed (``llmd_tpu.kv.subscriber``),
the precise/approx router producers (``llmd_tpu.kv.plugins`` /
``llmd_tpu.router.scorers``), and the P/D transfer wire
(``llmd_tpu.disagg.transfer``) — into one operator-switchable subsystem
(reference: precise-prefix-cache-routing/ + tiered-prefix-cache/):

- ``llmd_tpu.kvplane.plane`` — ``KVPlane``: mode resolution from
  ``LLMD_KV_PLANE`` (``precise`` | ``approx`` | ``off``), producer/scorer
  swap on the live scheduler, per-request degradation to the approx LRU when
  the index is cold/stale, and cross-engine pull planning (``plan_pull``).
- ``llmd_tpu.kvplane.pull`` — engine-side halves: the ``prefix_provider``
  serving a peer's ``pull_prefix`` and the puller that injects + credits the
  local prefix cache (failure NEVER fails the request — the admission ladder
  falls through to the host/disk offload tier, then plain re-prefill).
"""

from llmd_tpu.kvplane.plane import (  # noqa: F401
    LABEL_KV_TRANSFER_ADDR,
    LABEL_KV_TRANSFER_PORT,
    STATE_KV_PLANE,
    KVPlane,
    KVPlaneProducer,
    plane_mode,
)
from llmd_tpu.kvplane.pull import pull_prefix_into, serve_prefix  # noqa: F401
