"""Router side of the global KV plane.

``KVPlane`` is built once per RouterServer from the ``LLMD_KV_PLANE`` env knob
and installed onto the live scheduler:

- ``precise``: every ``approx-prefix-cache-producer`` in the config is replaced
  by a ``KVPlaneProducer`` (event-fed index lookups, degrading per-request to
  the approx LRU while the index is cold or the event feed stale), every plain
  ``prefix-cache-scorer`` by the tier-weighted precise scorer, and the router
  stamps cross-engine prefix pulls (``plan_pull``) onto requests routed past a
  better-indexed peer.
- ``approx``: the operator kill switch — precise producers/scorers in the
  config are swapped back to the approx pair; no index, no pulls.
- ``off`` (default when unset): the plane is inert; the config graph behaves
  bitwise-identically to a build without this subsystem.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from llmd_tpu.core.endpoint import Endpoint, EndpointPool
from llmd_tpu.core.request import InferenceRequest
from llmd_tpu.kv.plugins import (
    CTX_KV_INDEX,
    PrecisePrefixCacheProducer,
    PrecisePrefixCacheScorer,
)
from llmd_tpu.router.plugins import DataProducer
from llmd_tpu.router.scorers import (
    STATE_BLOCK_KEYS,
    STATE_PREFIX_HITS,
    ApproxPrefixCacheProducer,
    PrefixCacheScorer,
)

# Endpoint labels advertising the engine's KV-transfer side channel (the
# kv_events_* analogues; port-only label uses the endpoint host).
LABEL_KV_TRANSFER_ADDR = "kv_transfer_address"
LABEL_KV_TRANSFER_PORT = "kv_transfer_port"

STATE_KV_PLANE = "kv_plane_path"  # "precise" | "degraded" (unset when inert)

ENV_MODE = "LLMD_KV_PLANE"
ENV_PULL_THRESHOLD = "LLMD_KV_PLANE_PULL_THRESHOLD_BLOCKS"
ENV_STALE_S = "LLMD_KV_PLANE_STALE_S"

MODES = ("off", "approx", "precise")


def plane_mode() -> str:
    """Resolve the plane mode from ``LLMD_KV_PLANE`` (unset/unknown → off)."""
    mode = os.environ.get("LLMD_KV_PLANE", "off").strip().lower()
    return mode if mode in MODES else "off"


def transfer_address(ep: Endpoint) -> tuple[Optional[str], Optional[int]]:
    """(host, port) of an endpoint's KV-transfer side channel, from labels."""
    addr = ep.labels.get(LABEL_KV_TRANSFER_ADDR)
    if addr and ":" in addr:
        host, port = addr.rsplit(":", 1)
        try:
            return host, int(port)
        except ValueError:
            return None, None
    port_s = ep.labels.get(LABEL_KV_TRANSFER_PORT)
    if port_s:
        try:
            return ep.host, int(port_s)
        except ValueError:
            return None, None
    return None, None


class KVPlaneProducer(DataProducer):
    """Precise producer with built-in degradation to the approx LRU.

    Chooses per request: the event-fed index when it is warm (``precise``),
    the router-side LRU otherwise (``degraded``). The path taken is recorded
    in ``req.state[STATE_KV_PLANE]`` so pull planning only ever acts on
    index-backed hits, and ``pre_request`` warms whichever model produced.
    """

    def __init__(self, ctx: dict[str, Any], plane: "KVPlane",
                 blockSize: int = 16,
                 precise_params: Optional[dict[str, Any]] = None,
                 approx_params: Optional[dict[str, Any]] = None) -> None:
        self.plane = plane
        self.block_size = blockSize
        self.precise = PrecisePrefixCacheProducer(
            ctx, blockSize=blockSize, **(precise_params or {}))
        self.approx = ApproxPrefixCacheProducer(
            ctx, blockSize=blockSize, **(approx_params or {}))
        plane.block_size = blockSize

    def produce(self, req: InferenceRequest, endpoints: list[Endpoint]) -> None:
        stats = self.plane.stats
        if self.plane.index_ready():
            self.precise.produce(req, endpoints)
            req.state[STATE_KV_PLANE] = "precise"
            stats["precise_requests"] += 1
            stats["lookups"] += 1
            hits = req.state.get(STATE_PREFIX_HITS) or {}
            if any(v > 0 for v in hits.values()):
                stats["lookup_hits"] += 1
        else:
            self.approx.produce(req, endpoints)
            req.state[STATE_KV_PLANE] = "degraded"
            stats["degraded_requests"] += 1

    def pre_request(self, req: InferenceRequest, endpoint: Endpoint) -> None:
        # warm only the model that produced this request's keys: the two paths
        # hash under different lora terms, so cross-feeding stores dead keys
        if req.state.get(STATE_KV_PLANE) == "precise":
            self.precise.pre_request(req, endpoint)
        else:
            self.approx.pre_request(req, endpoint)


class KVPlane:
    """Mode resolution + scheduler install + cross-engine pull planning."""

    def __init__(self, mode: str, ctx: dict[str, Any], pool: EndpointPool,
                 pull_threshold_blocks: int = 4, stale_s: float = 30.0) -> None:
        self.mode = mode
        self.ctx = ctx
        self.pool = pool
        self.pull_threshold_blocks = pull_threshold_blocks
        self.stale_s = stale_s  # 0 disables the staleness check
        self.block_size = 16  # overwritten by the installed producer
        self.subscriber = None  # KVEventSubscriberManager, set by RouterServer
        self.swaps: list[str] = []  # "name: old-type->new-type" install log
        self.stats = {
            "precise_requests": 0, "degraded_requests": 0,
            "lookups": 0, "lookup_hits": 0, "pulls_planned": 0,
            "durable_pulls_planned": 0,
        }
        # durable-tier probe: a DurableStoreClient (kv/writeback.py) the
        # ladder consults when no live peer qualifies — the store outlives
        # replica churn, so its answer survives where the index's cannot
        self.durable_probe = None
        self._feed_batches = -1  # last observed subscriber batch count
        self._feed_seen_t = time.monotonic()

    @classmethod
    def from_env(cls, ctx: dict[str, Any], pool: EndpointPool) -> "KVPlane":
        mode = plane_mode()
        thr = int(os.environ.get("LLMD_KV_PLANE_PULL_THRESHOLD_BLOCKS", "4"))
        stale = float(os.environ.get("LLMD_KV_PLANE_STALE_S", "30"))
        plane = cls(mode, ctx, pool, pull_threshold_blocks=thr, stale_s=stale)
        if mode == "precise":
            from llmd_tpu.kv.writeback import (DurableStoreClient,
                                               DurableStoreConfig)

            durable_cfg = DurableStoreConfig.from_env()
            if durable_cfg.enabled:
                plane.durable_probe = DurableStoreClient(durable_cfg)
        return plane

    @property
    def active(self) -> bool:
        return self.mode == "precise"

    @property
    def index(self):
        return self.ctx.get(CTX_KV_INDEX)

    # ------------------------------------------------------------- install
    def install(self, scheduler) -> list[str]:
        """Swap producers/scorers on a built Scheduler according to the mode.

        ``off`` is a strict no-op: the scheduler keeps the exact plugin
        instances the config graph built.
        """
        if self.mode == "off":
            return []
        replaced = False
        for name, plugin in list(scheduler.plugins.items()):
            if self.mode == "precise":
                if isinstance(plugin, ApproxPrefixCacheProducer):
                    scheduler.plugins[name] = KVPlaneProducer(
                        scheduler.ctx, self, blockSize=plugin.block_size)
                    self.swaps.append(f"{name}: approx-producer->kv-plane-producer")
                    replaced = True
                elif isinstance(plugin, PrecisePrefixCacheProducer):
                    # already precise in config: wrap it so degradation +
                    # path marking still apply (reuse its shared ctx index)
                    wrapper = KVPlaneProducer(scheduler.ctx, self,
                                              blockSize=plugin.block_size)
                    wrapper.precise = plugin
                    scheduler.plugins[name] = wrapper
                    self.swaps.append(f"{name}: precise-producer->kv-plane-producer")
                    replaced = True
                elif isinstance(plugin, PrefixCacheScorer):
                    scheduler.plugins[name] = PrecisePrefixCacheScorer()
                    self.swaps.append(f"{name}: prefix-scorer->precise-scorer")
                    replaced = True
            elif self.mode == "approx":
                if isinstance(plugin, (PrecisePrefixCacheProducer, KVPlaneProducer)):
                    scheduler.plugins[name] = ApproxPrefixCacheProducer(
                        scheduler.ctx, blockSize=plugin.block_size)
                    self.swaps.append(f"{name}: precise-producer->approx-producer")
                    replaced = True
                elif isinstance(plugin, PrecisePrefixCacheScorer):
                    scheduler.plugins[name] = PrefixCacheScorer()
                    self.swaps.append(f"{name}: precise-scorer->prefix-scorer")
                    replaced = True
        if replaced:
            self._rebuild(scheduler)
        return self.swaps

    @staticmethod
    def _rebuild(scheduler) -> None:
        """Re-derive profiles/producer lists after a plugin swap (mirrors
        Scheduler.__init__'s wiring, same plugin-name references)."""
        from llmd_tpu.router.scheduler import Profile

        for prof in scheduler.config.scheduling_profiles:
            entries = [(scheduler.plugins[r.plugin_ref], r.weight)
                       for r in prof.plugins]
            scheduler.profiles[prof.name] = Profile(prof.name, entries)
        scheduler.producers = [p for p in scheduler.plugins.values()
                               if isinstance(p, DataProducer)]

    # ------------------------------------------------------------- health
    def index_ready(self) -> bool:
        """True when the index can answer precisely: non-empty, and the event
        feed has delivered within ``stale_s`` of its last delivery change."""
        idx = self.index
        if idx is None or len(idx) == 0:
            return False  # cold
        sub = self.subscriber
        if sub is not None and self.stale_s > 0:
            now = time.monotonic()
            batches = sub.batches_received
            if batches != self._feed_batches:
                self._feed_batches = batches
                self._feed_seen_t = now
            elif now - self._feed_seen_t > self.stale_s:
                return False  # feed stale: no batch movement in stale_s
        return True

    def feed_age_s(self) -> float:
        """Seconds since the event feed last showed batch movement (0 while
        batches keep arriving) — the index-staleness gauge/alert input."""
        sub = self.subscriber
        if sub is None:
            return 0.0
        now = time.monotonic()
        if sub.batches_received != self._feed_batches:
            # movement since last check: index_ready() will re-stamp; report
            # fresh without mutating its bookkeeping here
            return 0.0
        return max(0.0, now - self._feed_seen_t)

    # ------------------------------------------------------------- pulls
    def plan_pull(self, req: InferenceRequest, target_address: str) -> Optional[dict]:
        """KV-transfer params to stamp on ``req`` bound for ``target_address``,
        or None. Fires only on index-backed hits when a peer holds at least
        ``pull_threshold_blocks`` more prefix than the chosen target and
        advertises a transfer side channel."""
        if not self.active or req.state.get(STATE_KV_PLANE) != "precise":
            return None
        keys = req.state.get(STATE_BLOCK_KEYS) or []
        hits = req.state.get(STATE_PREFIX_HITS) or {}
        if not keys:
            return None
        target_tokens = int(hits.get(target_address, 0))
        peer_addr, peer_tokens = None, target_tokens
        for addr, h in hits.items():
            if addr != target_address and h > peer_tokens:
                peer_addr, peer_tokens = addr, int(h)
        bs = max(1, self.block_size)
        if peer_addr is None:
            # no live peer holds more than the target: the durable-tier rung.
            # The store's probe (tight deadline, breaker-guarded) stands in
            # for the index — its contents survive the churn that emptied it.
            return self._plan_durable_pull(req, keys, target_tokens, bs)
        if peer_tokens - target_tokens < self.pull_threshold_blocks * bs:
            return None
        ep = self.pool.get(peer_addr)
        if ep is None:
            return None
        host, port = transfer_address(ep)
        if host is None or port is None:
            return None
        n_blocks = min(len(keys), peer_tokens // bs)
        if n_blocks <= 0:
            return None
        self.stats["pulls_planned"] += 1
        return {
            "do_prefix_pull": True,
            "remote_host": host,
            "remote_port": port,
            "remote_request_id": req.request_id,
            "num_blocks": n_blocks,
            "block_hashes": keys[:n_blocks],
            # observability only; the router pops both before stamping
            # (engines would ignore them anyway). saved_tokens_est is the
            # re-prefill the pull avoids: prefix the peer holds beyond what
            # the chosen target already had — the decision ledger weighs it
            # against kv_transfer_prefix_pull_seconds actually spent.
            "peer": peer_addr,
            "saved_tokens_est": peer_tokens - target_tokens,
        }

    def _plan_durable_pull(self, req: InferenceRequest, keys: list[int],
                           target_tokens: int, bs: int) -> Optional[dict]:
        """Durable-store rung of the pull ladder: probe the cluster store for
        the consecutive prefix and stamp a tier="durable" pull when it beats
        the target by the same threshold a peer would have to. The engine
        resolves the stamp against its own client — the router never moves
        KV bytes, it only routes the decision."""
        if self.durable_probe is None:
            return None
        found = self.durable_probe.probe(keys)
        if found <= 0:
            return None
        if found * bs - target_tokens < self.pull_threshold_blocks * bs:
            return None
        self.stats["durable_pulls_planned"] += 1
        return {
            "do_prefix_pull": True,
            "tier": "durable",
            "num_blocks": found,
            "block_hashes": keys[:found],
            "peer": "durable-store",
            "saved_tokens_est": found * bs - target_tokens,
        }
