"""Engine side of the global KV plane: serving and consuming prefix pulls.

Both halves ride the existing P/D transfer wire (``disagg/transfer.py``):

- ``serve_prefix`` is the body of ``KVTransferSource.prefix_provider`` — a
  peer asked for a block-hash chain; stage whatever consecutive prefix of it
  is resident in the local prefix cache (two-phase: dispatch gathers under
  the engine lock, drain bytes off it, like the P/D export path).
- ``pull_prefix_into`` is the puller: fetch the peer's resident prefix,
  inject it into the local cache (hash-chain verified), and notify so the
  peer frees the registration. Any failure returns 0 — the caller's
  admission ladder then falls through to the host/disk offload tier and
  finally plain re-prefill; a failed pull must never fail the request.
"""

from __future__ import annotations

from typing import Optional, Sequence

from llmd_tpu.disagg.transfer import (
    KVTransferParams,
    drain_staged,
    inject_into_engine,
    prefix_export_begin,
)


def serve_prefix(server, block_hashes: Sequence[int],
                 request_id: str) -> Optional[tuple]:
    """``prefix_provider`` body for an EngineServer: resolve + stage + drain
    the locally resident prefix of ``block_hashes``. Runs on the transfer
    source's serving thread (blocking is fine; only the dispatch phase takes
    the engine lock). Returns ``(hashes, token_chunks, blocks)`` or None."""
    staged = server.async_engine.run_locked(
        lambda: prefix_export_begin(
            server.engine, request_id, block_hashes,
            staging_pages=server.engine.cfg.offload_staging_blocks))
    if staged is None:
        return None
    blocks = drain_staged(staged.parts)
    return staged.hashes, staged.chunks, blocks


def pull_prefix_into(server, ktp: KVTransferParams, token_ids: list[int],
                     lora_id: Optional[str] = None,
                     mm_hashes: Sequence[bytes] = ()) -> tuple[int, str, bool]:
    """Pull the stamped prefix chain from the peer and commit it locally.

    Returns ``(blocks_injected, outcome, peer_released)`` with outcome one of
    ``hit`` / ``empty`` (peer served but nothing committed) / ``miss`` (peer
    holds none of the chain) / ``peer_dead`` / ``error`` (inject failed).
    ``peer_released`` False means the peer may still hold a registration under
    ``ktp.remote_request_id`` — the caller must release it on request retire.
    """
    try:
        pulled = server.transfer_client.pull_prefix(
            ktp.remote_host, ktp.remote_port, ktp.remote_request_id,
            ktp.block_hashes)
    except Exception:
        return 0, "peer_dead", False
    if pulled is None:
        return 0, "miss", True  # peer registered nothing on a miss
    n, outcome = 0, "error"
    try:
        n = server.async_engine.run_locked(
            lambda: inject_into_engine(server.engine, pulled, token_ids,
                                       lora_id, mm_hashes))
        outcome = "hit" if n else "empty"
    except Exception:
        pass  # degrade to recompute; the notify below still frees the peer
    try:
        released = bool(server.transfer_client.notify(
            ktp.remote_host, ktp.remote_port, ktp.remote_request_id))
    except Exception:
        released = False
    return n, outcome, released
