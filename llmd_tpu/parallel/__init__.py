"""Mesh/sharding layer: TP over ICI, DP across replicas/slices, EP for MoE, SP for
long context. The TPU-native answer to the reference's NCCL/NVSHMEM/MPI stack
(SURVEY.md §5 'Distributed communication backend'): XLA collectives inserted by GSPMD
from sharding annotations, shard_map for explicit all-to-all in the MoE path.
"""

from llmd_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    shard_pytree,
    ShardingRules,
    DEFAULT_RULES,
)
