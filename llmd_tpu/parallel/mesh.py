"""Device mesh construction + logical-axis sharding rules.

Replaces the reference's launcher-driven parallelism flags
(--tensor-parallel-size / --data-parallel-size / --enable-expert-parallel,
wide-ep-lws decode.yaml:85-121) with a declarative mesh:

- ``tp``  — tensor parallel over ICI (MXU-feeding matmul shards)
- ``ep``  — expert parallel for MoE (all-to-all over ICI)
- ``dp``  — data parallel across replicas/slices (DCN or ICI)
- ``sp``  — sequence parallel for long-context prefill (ring over ICI)

GSPMD inserts psum/all-gather/reduce-scatter/all-to-all from these annotations — no
hand-written NCCL calls anywhere (scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.ep * self.tp * self.sp

    def axis_names(self) -> tuple[str, ...]:
        return ("dp", "sp", "ep", "tp")


def build_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with axes (dp, sp, ep, tp); tp innermost so it rides the
    fastest ICI links, dp outermost so it can span DCN (cross-slice)."""
    devs = list(devices if devices is not None else jax.devices())
    n = cfg.num_devices
    if len(devs) < n:
        raise ValueError(f"need {n} devices for {cfg}, have {len(devs)}")
    arr = np.array(devs[:n]).reshape(cfg.dp, cfg.sp, cfg.ep, cfg.tp)
    return Mesh(arr, cfg.axis_names())


# Logical axis name → mesh axis (None = replicated). The model annotates params and
# activations with logical names; these rules bind them to the physical mesh.
@dataclass(frozen=True)
class ShardingRules:
    rules: tuple[tuple[str, Optional[str]], ...] = ()

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        m = dict(self.rules)
        return P(*[m.get(a) if a is not None else None for a in logical_axes])

    def sharding(self, mesh: Mesh, logical_axes: Sequence[Optional[str]]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes))


DEFAULT_RULES = ShardingRules(rules=(
    ("batch", "dp"),
    ("sequence", "sp"),          # sequence-parallel long-context prefill
    ("vocab", "tp"),
    ("embed", None),             # hidden dim replicated (activations)
    ("heads", "tp"),             # attention heads → tp (Megatron-style column parallel)
    ("kv_heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),               # ffn intermediate → tp
    ("experts", "ep"),           # MoE expert dim → ep
    ("expert_mlp", "tp"),        # within-expert ffn → tp
    ("kv_pages", None),
    ("layers", None),
    ("lora_slots", None),        # adapter bank replicated across the mesh
))


def shard_pytree(tree, mesh: Mesh, axes_tree, rules: ShardingRules = DEFAULT_RULES):
    """device_put every leaf with the NamedSharding derived from its logical axes."""
    def _put(x, axes):
        return jax.device_put(x, rules.sharding(mesh, axes))
    return jax.tree.map(_put, tree, axes_tree, is_leaf=lambda x: x is None)
