"""EPLB — expert-parallel load balancing with redundant experts.

TPU-native equivalent of the reference's vLLM EPLB config
(`guides/wide-ep-lws/modelserver/gpu/vllm/base/decode.yaml:114-118`:
``--enable-eplb {"window_size":1000, "step_interval":3000,
"num_redundant_experts":32}``). The reference rebalances which GPU hosts which
expert; here the expert ("slot") dimension of the MoE weights is sharded over the
``ep`` mesh axis, so *slot order is placement*: slots ``[r*S/ep : (r+1)*S/ep]``
live on EP rank ``r``. Rebalancing = recomputing ``slot_to_expert`` and
re-gathering physical weights from the logical master copy (one device gather per
rebalance, off the hot path — the step programs never recompile because shapes
are static).

Algorithm (DeepSeek-EPLB-shaped, greedy):
1. every expert keeps >= 1 slot; the ``num_redundant_experts`` extra slots go one
   at a time to the expert with the highest per-replica load;
2. replica instances (load = expert_load / n_replicas) are placed onto EP ranks
   longest-processing-time-first, replicas of one expert spread across ranks.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class EPLBConfig:
    window_size: int = 1000        # engine steps of load stats retained
    step_interval: int = 3000      # engine steps between rebalances
    num_redundant_experts: int = 32


class ExpertLoadTracker:
    """Sliding window of per-layer per-expert routed-token counts."""

    def __init__(self, num_layers: int, num_experts: int, window_size: int) -> None:
        self.window: deque[np.ndarray] = deque(maxlen=window_size)
        self.num_layers = num_layers
        self.num_experts = num_experts

    def record(self, counts: np.ndarray) -> None:
        """counts: [L, E] tokens routed to each expert this step."""
        assert counts.shape == (self.num_layers, self.num_experts), counts.shape
        self.window.append(np.asarray(counts, np.int64))

    def loads(self) -> np.ndarray:
        """[L, E] windowed load, +1 smoothing so idle experts keep a floor."""
        if not self.window:
            return np.ones((self.num_layers, self.num_experts), np.int64)
        return np.sum(self.window, axis=0) + 1


def assign_replica_counts(loads: np.ndarray, num_slots: int) -> np.ndarray:
    """loads: [E] -> replica count per expert, sum == num_slots, each >= 1.

    Greedy: repeatedly give the next redundant slot to the expert whose
    per-replica load is currently highest.
    """
    E = loads.shape[0]
    if num_slots < E:
        raise ValueError(f"num_slots {num_slots} < num_experts {E}")
    counts = np.ones((E,), np.int64)
    loads = loads.astype(np.float64)
    for _ in range(num_slots - E):
        counts[np.argmax(loads / counts)] += 1
    return counts


def place_slots(loads: np.ndarray, replica_counts: np.ndarray, ep_size: int) -> np.ndarray:
    """LPT placement of replica instances onto EP ranks.

    Returns ``slot_to_expert`` [S] with S = sum(replica_counts); slots are laid out
    rank-major (slots of rank r are contiguous) so sharding the slot dim over ``ep``
    realises the placement. Replicas of one expert land on distinct ranks while
    rank capacity allows.
    """
    S = int(replica_counts.sum())
    if S % ep_size != 0:
        raise ValueError(f"total slots {S} not divisible by ep_size {ep_size}")
    per_rank = S // ep_size
    # replica instances, heaviest first
    inst = []  # (per-replica load, expert)
    for e, c in enumerate(replica_counts):
        inst.extend([(loads[e] / c, e)] * int(c))
    inst.sort(key=lambda t: -t[0])

    rank_load = np.zeros((ep_size,), np.float64)
    rank_slots: list[list[int]] = [[] for _ in range(ep_size)]
    rank_has: list[set[int]] = [set() for _ in range(ep_size)]
    for load, e in inst:
        order = np.argsort(rank_load, kind="stable")
        # prefer the least-loaded rank that has room and no replica of e yet
        pick = next(
            (r for r in order if len(rank_slots[r]) < per_rank and e not in rank_has[r]),
            next(r for r in order if len(rank_slots[r]) < per_rank),
        )
        rank_slots[pick].append(e)
        rank_has[pick].add(e)
        rank_load[pick] += load
    return np.concatenate([np.asarray(s, np.int32) for s in rank_slots])


def rebalance(loads: np.ndarray, num_slots: int, ep_size: int
              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-layer rebalance. loads: [L, E].

    Returns (slot_to_expert [L, S], replica_slots [L, E, R], replica_counts [L, E])
    where R = max replicas any expert got; ``replica_slots[l, e, i % counts[l, e]]``
    is a valid slot for expert e (unused tail entries repeat the first slot so any
    index is safe).
    """
    L, E = loads.shape
    s2e = np.zeros((L, num_slots), np.int32)
    counts = np.zeros((L, E), np.int32)
    for l in range(L):
        rc = assign_replica_counts(loads[l], num_slots)
        s2e[l] = place_slots(loads[l], rc, ep_size)
        counts[l] = rc
    R = int(counts.max())
    slots = np.zeros((L, E, R), np.int32)
    for l in range(L):
        for e in range(E):
            mine = np.nonzero(s2e[l] == e)[0]
            slots[l, e, : len(mine)] = mine
            slots[l, e, len(mine):] = mine[0]  # safe pad
    return s2e, slots, counts


def balance_ratio(loads: np.ndarray, slot_to_expert: np.ndarray,
                  replica_counts: np.ndarray, ep_size: int) -> float:
    """max/mean per-rank load under the placement (1.0 = perfect). loads: [E]."""
    S = slot_to_expert.shape[0]
    per_rank = S // ep_size
    per_slot = loads[slot_to_expert] / replica_counts[slot_to_expert]
    rank_loads = per_slot.reshape(ep_size, per_rank).sum(axis=1)
    return float(rank_loads.max() / max(rank_loads.mean(), 1e-9))
