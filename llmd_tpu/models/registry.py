"""Named model configs: test-size + flagship serving shapes.

The reference's guides serve Qwen3-32B (optimized-baseline), Llama-3-70B / gpt-oss-120b
(pd-disaggregation), DeepSeek-R1 (wide-ep-lws) via vLLM; here each family maps to a
config of our stack. Sizes marked `-sim` are scaled to fit the available chip while
keeping the architectural shape (GQA ratios, MoE top-k) of the original.
"""

from __future__ import annotations

from llmd_tpu.models.config import ModelConfig

MODEL_REGISTRY: dict[str, ModelConfig] = {
    # CI-size models (CPU-runnable, byte-level vocab)
    "tiny": ModelConfig(
        name="tiny", vocab_size=288, hidden_size=128, intermediate_size=384,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
    ),
    # VL shape for the encode-disagg (E/PD) path: tiny text stack + a real
    # (random-init) vision tower; 4 embedding tokens per media item.
    "tiny-vl": ModelConfig(
        name="tiny-vl", vocab_size=288, hidden_size=128, intermediate_size=384,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
        mm_tokens=4, mm_placeholder_id=287, vision_patch=8, vision_image_size=32,
        vision_layers=2, vision_hidden=64, vision_heads=4,
    ),
    # Llama-3.2-ratio GQA at CI size: head_dim 64 (lane pad = one extra head)
    # exercises the packed KV layout (ops/packed_kv) on the serving surface.
    "tiny64": ModelConfig(
        name="tiny64", vocab_size=288, hidden_size=128, intermediate_size=384,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=64,
    ),
    "tiny-moe": ModelConfig(
        name="tiny-moe", vocab_size=288, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
        moe_num_experts=8, moe_top_k=2, moe_intermediate_size=128,
        moe_num_shared_experts=1,
    ),
    # Flagship single-chip bench model (~1.1B params bf16 ≈ 2.2GB — fits v5e 16GB HBM
    # with room for KV pages). Llama-3.2-1B-shaped.
    "llama-1b": ModelConfig(
        name="llama-1b", vocab_size=32768, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        rope_theta=500000.0, tie_embeddings=True,
    ),
    # Llama-3-8B shape (multi-chip TP target).
    "llama-8b": ModelConfig(
        name="llama-8b", vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        rope_theta=500000.0, tie_embeddings=False,
    ),
    # Qwen3-32B shape (optimized-baseline parity target).
    "qwen-32b": ModelConfig(
        name="qwen-32b", vocab_size=151936, hidden_size=5120, intermediate_size=25600,
        num_layers=64, num_heads=64, num_kv_heads=8, head_dim=128,
        rope_theta=1000000.0, tie_embeddings=False,
    ),
    # DeepSeek-R1-class MoE shape scaled for wide-EP dry-runs (shape, not size).
    "moe-wide-sim": ModelConfig(
        name="moe-wide-sim", vocab_size=32768, hidden_size=1024, intermediate_size=2048,
        num_layers=4, num_heads=16, num_kv_heads=4, head_dim=64,
        moe_num_experts=32, moe_top_k=4, moe_intermediate_size=512,
        moe_num_shared_experts=1,
    ),
    # MLA at CI size (DeepSeek-V2/V3 attention family; ratios mirror V3's
    # 512-rank / 64-rope / 128-nope / 128-value at 1/8 scale).
    "tiny-mla": ModelConfig(
        name="tiny-mla", vocab_size=288, hidden_size=128, intermediate_size=384,
        num_layers=2, num_heads=4, num_kv_heads=4, head_dim=32,
        mla_kv_lora_rank=64, mla_rope_dim=16, mla_qk_nope_dim=16,
        mla_v_head_dim=16,
    ),
    # MLA x MoE at CI size: the wide-EP north-star STACK (latent attention +
    # expert banks) cheap enough for the multichip dryrun and stress tests.
    "tiny-mla-moe": ModelConfig(
        name="tiny-mla-moe", vocab_size=288, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=4,
        head_dim=32, mla_kv_lora_rank=64, mla_rope_dim=16, mla_qk_nope_dim=16,
        mla_v_head_dim=16, moe_num_experts=8, moe_top_k=2,
        moe_intermediate_size=128, moe_num_shared_experts=1,
    ),
    # DeepSeek-R1/V3-class wide-EP shape with TRUE MLA latent KV (shape-
    # faithful scaled stand-in for the reference's north-star model,
    # guides/wide-ep-lws/README.md): per-token KV is rank+rope = 160 floats
    # shared across all heads vs 2*4*64 = 512 for the GQA sim above.
    "moe-wide-mla": ModelConfig(
        name="moe-wide-mla", vocab_size=32768, hidden_size=1024,
        intermediate_size=2048, num_layers=4, num_heads=16, num_kv_heads=16,
        head_dim=64, mla_kv_lora_rank=128, mla_rope_dim=32,
        mla_qk_nope_dim=32, mla_v_head_dim=32,
        moe_num_experts=32, moe_top_k=4, moe_intermediate_size=512,
        moe_num_shared_experts=1,
    ),
}


def get_model_config(name: str) -> ModelConfig:
    try:
        return MODEL_REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}") from None
