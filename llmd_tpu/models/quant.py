"""Weight-only int8 quantization for the decode-bandwidth-bound serving regime.

Decode reads every weight byte once per step — on a v5e the 819 GB/s HBM
ceiling, not the MXU, bounds single-chip decode throughput (bench.py's
weights-BW utilization). Symmetric per-output-channel int8 halves the weight
bytes against bf16, so the decode roofline doubles, at the cost of a <0.5%-
scale per-channel rounding error. The reference's headline baselines serve
fp8 on B200 (BASELINE.md row 5) — reduced-precision weights are parity, not
a shortcut.

Formulation keeps HBM traffic int8 end to end: with a per-OUTPUT-channel
scale ``s``, ``x @ (w_int8 * s) == (x @ w_int8) * s`` exactly, so the dot
consumes the int8 tensor (XLA fuses the int8→bf16 convert into the dot's
operand stream — no dequantized copy is ever materialised in HBM) and the
scale applies to the matmul OUTPUT, a [*, out] elementwise multiply that
fuses into the surrounding graph.

Quantized: the dense per-layer projections (wq/wk/wv/wo, wi/wo_mlp), the
MoE expert banks and shared experts (per-expert per-output-channel scales;
the expert GEMMs then run the einsum path — the Pallas grouped GEMM is
bf16-only), and the unembedding. Kept bf16: norms, biases and the router
(tiny), embed (gather table; also the tie_embeddings source), LoRA deltas
(numerically delicate low-rank). EPLB composes: the redundant-expert
regather moves each slot's weights and its per-expert scales by the same
slot map (engine._eplb_rebalance).

Cited reference behavior: quantized serving is table stakes in the
reference's model servers (vLLM --quantization; fp8 checkpoints on GPU).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

# key → axis NAMES contracted by its matmul (from param_logical_axes); the
# scale lives on every remaining (output/batch) axis — for expert banks that
# includes the experts axis, i.e. per-expert per-output-channel scales
_CONTRACT: dict[str, tuple[str, ...]] = {
    "wq": ("embed",),
    "wk": ("embed",),
    "wv": ("embed",),
    "wo": ("heads", "head_dim"),
    "wi": ("embed",),
    "wo_mlp": ("mlp",),
    "moe_wi": ("embed",),
    "moe_wo": ("expert_mlp",),
    "shared_wi": ("embed",),
    "shared_wo": ("mlp",),
    # (the unembedding quantizes via its own branch below: its source can be
    # embed.T under tie_embeddings, which has no entry in the axes dict)
}

QUANTIZABLE_LAYER_KEYS = ("wq", "wk", "wv", "wo", "wi", "wo_mlp",
                          "moe_wi", "moe_wo", "shared_wi", "shared_wo")


def _quantize_one(w: jax.Array, contract_axes: tuple[int, ...]):
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=contract_axes)


def quantize_params(cfg, params: dict[str, jax.Array],
                    base_axes: Optional[dict[str, Any]] = None,
                    ) -> tuple[dict[str, jax.Array], dict[str, Any]]:
    """Replace quantizable leaves with ``<key>_q`` int8 + ``<key>_scale`` f32.

    Returns (new params, logical-axes dict matching the NEW tree) so meshed
    engines can shard the quantized leaves exactly like their bf16 ancestors
    (scale axes = the weight's non-contracted axes).
    """
    from llmd_tpu.models.transformer import param_logical_axes

    axes = dict(base_axes or param_logical_axes(cfg))
    out = dict(params)
    for key in QUANTIZABLE_LAYER_KEYS:
        if key not in out:
            continue
        names = axes[key]
        contract = tuple(i for i, n in enumerate(names) if n in _CONTRACT[key])
        q, s = _quantize_one(out.pop(key), contract)
        out[key + "_q"], out[key + "_scale"] = q, s
        axes[key + "_q"] = names
        axes[key + "_scale"] = tuple(n for n in names if n not in _CONTRACT[key])
        del axes[key]

    # unembedding: the [D, V] logits matmul is ~6-10% of a dense model's
    # decode bytes. tie_embeddings models read embed.T — keep embed (the
    # gather table) bf16 and carry an int8 copy for the logits path.
    src = params["embed"].T if cfg.tie_embeddings else out.pop("unembed", None)
    if src is not None:
        q, s = _quantize_one(src, (0,))
        out["unembed_q"], out["unembed_scale"] = q, s
        axes["unembed_q"] = ("embed", "vocab")
        axes["unembed_scale"] = ("vocab",)
        axes.pop("unembed", None)
    return out, axes
