"""HF checkpoint loading: config.json → ModelConfig, safetensors → stacked params.

The reference serves HF checkpoints (Qwen3-32B, Llama-70B, gpt-oss-120b —
/root/reference/guides/optimized-baseline/README.md:22-28,
guides/wide-ep-lws/README.md:406-414) through vLLM's weight loader; this module is
the TPU-native equivalent feeding our scanned-stack layout
(``llmd_tpu.models.transformer``): per-layer HF tensors are transposed into the
matmul-ready ``[D, H, Dh]``-style orientations and stacked into single
``[num_layers, ...]`` leaves so the layer stack runs under one ``lax.scan``.

Supported architectures (config.json ``architectures[0]``):
- ``LlamaForCausalLM`` / ``MistralForCausalLM`` — GQA, SwiGLU, optional tied embeddings
- ``Qwen2ForCausalLM`` — adds q/k/v projection biases
- ``Qwen3ForCausalLM`` — adds per-head q/k RMSNorm and an explicit ``head_dim``

Handles single-file ``model.safetensors`` and sharded
``model.safetensors.index.json`` checkpoints; weights are cast to the target dtype
(bfloat16 for serving — MXU-native; float32 for parity tests against the HF
reference implementation).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from llmd_tpu.models.config import ModelConfig

_ARCH_FAMILY = {
    "LlamaForCausalLM": "llama",
    "MistralForCausalLM": "llama",
    "Qwen2ForCausalLM": "qwen2",
    "Qwen3ForCausalLM": "qwen3",
}


def is_hf_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.isfile(os.path.join(path, "config.json"))


def config_from_hf(path: str, dtype: str = "bfloat16") -> ModelConfig:
    """Translate an HF ``config.json`` into our ``ModelConfig``."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    archs = hf.get("architectures") or []
    arch = archs[0] if archs else "LlamaForCausalLM"
    family = _ARCH_FAMILY.get(arch)
    if family is None:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: {sorted(_ARCH_FAMILY)}"
        )
    scaling = hf.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type", "default")) != "default":
        # Loading would succeed but produce silently wrong logits (scaled RoPE
        # frequencies are not applied) — refuse instead.
        raise ValueError(
            f"unsupported rope_scaling {scaling!r} in {path}; only default RoPE "
            "is implemented"
        )
    if hf.get("sliding_window") is not None and hf.get("use_sliding_window", True):
        # Same silent-corruption class: full attention past the window would
        # diverge from the reference implementation (Mistral-style checkpoints).
        raise ValueError(
            f"unsupported sliding_window={hf['sliding_window']} in {path}; "
            "full attention only"
        )
    D = int(hf["hidden_size"])
    H = int(hf["num_attention_heads"])
    return ModelConfig(
        name=os.path.basename(os.path.normpath(path)) or arch,
        vocab_size=int(hf["vocab_size"]),
        hidden_size=D,
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=H,
        num_kv_heads=int(hf.get("num_key_value_heads", H)),
        head_dim=int(hf.get("head_dim") or D // H),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-6)),
        max_position=int(hf.get("max_position_embeddings", 32768)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        dtype=dtype,
        qk_norm=family == "qwen3",
        # honour an explicit attention_bias on any family; qwen2's default is True
        attn_bias=bool(hf.get("attention_bias", family == "qwen2")),
    )


class _TensorSource:
    """Uniform tensor-by-name access over single-file or index-sharded safetensors.

    Reads stay on HOST memory (torch-CPU framework — handles bf16, which numpy
    can't): loading must never bounce checkpoint bytes through the accelerator;
    only the final stacked leaves are device_put once (as the serving dtype).
    """

    def __init__(self, path: str) -> None:
        from safetensors import safe_open

        self._open = safe_open
        self.path = path
        self._where: dict[str, str] = {}  # tensor name → shard file
        self._handles: dict[str, object] = {}
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.isfile(index):
            with open(index) as f:
                self._where = dict(json.load(f)["weight_map"])
        else:
            single = os.path.join(path, "model.safetensors")
            if not os.path.isfile(single):
                raise FileNotFoundError(
                    f"no model.safetensors or model.safetensors.index.json in {path}"
                )
            with safe_open(single, framework="torch", device="cpu") as f:
                for name in f.keys():
                    self._where[name] = "model.safetensors"

    def names(self) -> list[str]:
        return list(self._where)

    def get(self, name: str) -> np.ndarray:
        """Tensor as host float32 ndarray."""
        fname = self._where.get(name)
        if fname is None:
            raise KeyError(f"tensor {name!r} not in checkpoint {self.path}")
        h = self._handles.get(fname)
        if h is None:
            h = self._handles[fname] = self._open(
                os.path.join(self.path, fname), framework="torch", device="cpu"
            )
        import torch

        return h.get_tensor(name).to(torch.float32).numpy()


def load_params(
    path: str, cfg: Optional[ModelConfig] = None, dtype: Optional[str] = None
) -> dict[str, jax.Array]:
    """Load + restack checkpoint weights into the scanned-layer param dict.

    HF per-layer ``[out, in]`` projection matrices become matmul-ready stacked
    leaves: ``wq [L, D, H, Dh]``, ``wo [L, H, Dh, D]``, fused SwiGLU
    ``wi = concat(gate.T, up.T) [L, D, 2F]`` (our ``swiglu`` splits gate-first),
    ``wo_mlp [L, F, D]``; ``unembed`` is ``lm_head.T [D, V]`` unless embeddings
    are tied (then ``embed.T`` is used at unembed time, matching HF tying).
    """
    if cfg is None:
        cfg = config_from_hf(path, dtype=dtype or "bfloat16")
    dt = cfg.jax_dtype
    src = _TensorSource(path)
    D, H, Hk, Dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    L, F = cfg.num_layers, cfg.intermediate_size

    def g(name: str) -> np.ndarray:
        return src.get(name)

    def stack(fn) -> jax.Array:
        return jnp.asarray(np.stack([fn(l) for l in range(L)]), dt)

    p: dict[str, jax.Array] = {
        "embed": jnp.asarray(g("model.embed_tokens.weight"), dt),
        "final_norm": jnp.asarray(g("model.norm.weight"), dt),
        "attn_norm": stack(lambda l: g(f"model.layers.{l}.input_layernorm.weight")),
        "mlp_norm": stack(
            lambda l: g(f"model.layers.{l}.post_attention_layernorm.weight")
        ),
        "wq": stack(
            lambda l: g(f"model.layers.{l}.self_attn.q_proj.weight").T.reshape(D, H, Dh)
        ),
        "wk": stack(
            lambda l: g(f"model.layers.{l}.self_attn.k_proj.weight").T.reshape(D, Hk, Dh)
        ),
        "wv": stack(
            lambda l: g(f"model.layers.{l}.self_attn.v_proj.weight").T.reshape(D, Hk, Dh)
        ),
        "wo": stack(
            lambda l: g(f"model.layers.{l}.self_attn.o_proj.weight").T.reshape(H, Dh, D)
        ),
        "wi": stack(
            lambda l: np.concatenate(
                [
                    g(f"model.layers.{l}.mlp.gate_proj.weight").T,
                    g(f"model.layers.{l}.mlp.up_proj.weight").T,
                ],
                axis=-1,
            )
        ),
        "wo_mlp": stack(lambda l: g(f"model.layers.{l}.mlp.down_proj.weight").T),
    }
    if cfg.qk_norm:
        p["q_norm"] = stack(lambda l: g(f"model.layers.{l}.self_attn.q_norm.weight"))
        p["k_norm"] = stack(lambda l: g(f"model.layers.{l}.self_attn.k_norm.weight"))
    if cfg.attn_bias:
        p["bq"] = stack(
            lambda l: g(f"model.layers.{l}.self_attn.q_proj.bias").reshape(H, Dh)
        )
        p["bk"] = stack(
            lambda l: g(f"model.layers.{l}.self_attn.k_proj.bias").reshape(Hk, Dh)
        )
        p["bv"] = stack(
            lambda l: g(f"model.layers.{l}.self_attn.v_proj.bias").reshape(Hk, Dh)
        )
        # llama-style attention_bias puts a bias on o_proj too; qwen2 does not
        names = set(src.names())
        p["bo"] = (
            stack(lambda l: g(f"model.layers.{l}.self_attn.o_proj.bias"))
            if "model.layers.0.self_attn.o_proj.bias" in names
            else jnp.zeros((L, D), dt)
        )
    if not cfg.tie_embeddings:
        p["unembed"] = jnp.asarray(g("lm_head.weight").T, dt)
    expected_fused = D * 2 * F
    got = p["wi"].shape[1] * p["wi"].shape[2]
    if got != expected_fused:
        raise ValueError(
            f"mlp shape mismatch: fused gate/up is {p['wi'].shape}, "
            f"config expects [L, {D}, {2 * F}]"
        )
    return p


def load_model(
    path: str, dtype: str = "bfloat16"
) -> tuple[ModelConfig, dict[str, jax.Array]]:
    """One-call load: (ModelConfig, stacked params) from an HF checkpoint dir."""
    cfg = config_from_hf(path, dtype=dtype)
    return cfg, load_params(path, cfg)


def main() -> None:  # pragma: no cover - CLI convenience
    import argparse

    ap = argparse.ArgumentParser(description="inspect an HF checkpoint dir")
    ap.add_argument("path")
    args = ap.parse_args()
    cfg = config_from_hf(args.path)
    params = load_params(args.path, cfg)
    n = sum(int(np.prod(v.shape)) for v in params.values())
    print(f"{cfg.name}: {cfg.num_layers}L d={cfg.hidden_size} "
          f"H={cfg.num_heads}/{cfg.num_kv_heads} dh={cfg.head_dim} "
          f"vocab={cfg.vocab_size} tie={cfg.tie_embeddings} "
          f"qk_norm={cfg.qk_norm} attn_bias={cfg.attn_bias} — "
          f"{n / 1e9:.3f}B params")


if __name__ == "__main__":
    main()
