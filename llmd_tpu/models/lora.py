"""LoRA multi-adapter support — batched low-rank deltas on the attention path.

Parity: reference `docs/architecture/core/model-servers.md:55-75` (dynamic LoRA
serving + metrics contract) and `docs/operations/rollouts/adapter-rollout.md:11-31`
(runtime adapter updating via `VLLM_ALLOW_RUNTIME_LORA_UPDATING` +
`lora_filesystem_resolver`; canary via InferenceModelRewrite). TPU-shaped design:

- All adapters live in fixed-shape stacked tensors ``[n_slots, L, ...]`` — loading
  an adapter writes one slot (one ``.at[slot].set``), so the serving step programs
  never recompile as adapters come and go.
- Slot 0 is the permanent null adapter (B = 0 → exact base-model output); every
  request carries a per-sequence slot index, and a single batched gather applies
  the right delta per batch row: ``delta = (x @ A[idx]) @ B[idx] * (alpha/r)``.
- Targets q/k/v/o (the classic attention set). A and B are initialised
  Kaiming/zero as in the LoRA paper, so a freshly loaded random adapter is a
  realistic test double; real weights load through the same slot-write path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LoRAConfig:
    max_adapters: int = 8       # reference vllm:lora_requests_info max_lora
    rank: int = 8
    alpha: float = 16.0

    @property
    def n_slots(self) -> int:
        return self.max_adapters + 1  # slot 0 = null adapter

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


LORA_TARGETS = ("wq", "wk", "wv", "wo")


def lora_param_logical_axes(cfg) -> dict[str, tuple]:
    """Logical axes for the stacked adapter tensors (layers leading so the bank
    scans with the layer stack; slot dim replicated; output dim of B follows the
    base weight's tp sharding)."""
    axes = {}
    for t in LORA_TARGETS:
        axes[f"lora_A_{t}"] = ("layers", "lora_slots", "embed", None)
        out_axis = "embed" if t == "wo" else "heads"
        axes[f"lora_B_{t}"] = ("layers", "lora_slots", None, out_axis)
    return axes


def init_lora_params(model_cfg, lora_cfg: LoRAConfig) -> dict[str, jax.Array]:
    """All-zero adapter bank: every slot starts as the null adapter."""
    L, D = model_cfg.num_layers, model_cfg.hidden_size
    r, S = lora_cfg.rank, lora_cfg.n_slots
    dt = model_cfg.jax_dtype
    dims_out = {
        "wq": model_cfg.num_heads * model_cfg.head_dim,
        "wk": model_cfg.num_kv_heads * model_cfg.head_dim,
        "wv": model_cfg.num_kv_heads * model_cfg.head_dim,
        "wo": model_cfg.hidden_size,
    }
    # wo's input is the concatenated head output, not the hidden dim
    dims_in = {"wq": D, "wk": D, "wv": D,
               "wo": model_cfg.num_heads * model_cfg.head_dim}
    p: dict[str, jax.Array] = {}
    for t in LORA_TARGETS:
        p[f"lora_A_{t}"] = jnp.zeros((L, S, dims_in[t], r), dt)
        p[f"lora_B_{t}"] = jnp.zeros((L, S, r, dims_out[t]), dt)
    return p


def make_adapter_weights(model_cfg, lora_cfg: LoRAConfig, key: jax.Array,
                         targets: tuple[str, ...] = LORA_TARGETS) -> dict[str, jax.Array]:
    """One adapter's weights (LoRA init: A ~ Kaiming-ish normal, B = 0 would be a
    no-op — for test doubles B is also random so the adapter visibly changes
    outputs; real checkpoints replace both)."""
    L, D = model_cfg.num_layers, model_cfg.hidden_size
    r = lora_cfg.rank
    dt = model_cfg.jax_dtype
    dims_out = {
        "wq": model_cfg.num_heads * model_cfg.head_dim,
        "wk": model_cfg.num_kv_heads * model_cfg.head_dim,
        "wv": model_cfg.num_kv_heads * model_cfg.head_dim,
        "wo": model_cfg.hidden_size,
    }
    dims_in = {"wq": D, "wk": D, "wv": D,
               "wo": model_cfg.num_heads * model_cfg.head_dim}
    out = {}
    keys = iter(jax.random.split(key, 2 * len(targets)))
    for t in targets:
        out[f"lora_A_{t}"] = (
            jax.random.normal(next(keys), (L, dims_in[t], r), jnp.float32)
            * (dims_in[t] ** -0.5)
        ).astype(dt)
        out[f"lora_B_{t}"] = (
            jax.random.normal(next(keys), (L, r, dims_out[t]), jnp.float32) * 0.05
        ).astype(dt)
    return out


def apply_lora(h: jax.Array, A: jax.Array, B: jax.Array, idx: jax.Array,
               scale: float) -> jax.Array:
    """Per-token adapter delta. h: [N, Din] flat tokens; A: [S, Din, r];
    B: [S, r, Dout]; idx: [N] int32 slot per token. Returns [N, Dout]."""
    Ab = A[idx]  # [N, Din, r]
    Bb = B[idx]  # [N, r, Dout]
    xa = jnp.einsum("nd,ndr->nr", h, Ab)
    return jnp.einsum("nr,nrk->nk", xa, Bb) * scale


class LoRARegistry:
    """Name → slot mapping with ref-counting-free LRU of *inactive* adapters.

    The engine owns the device-side adapter bank; this class owns the naming,
    slot assignment, and the reference metrics contract fields
    (`vllm:lora_requests_info{max_lora, running_lora_adapters,
    waiting_lora_adapters}` — model-servers.md:64-75).
    """

    def __init__(self, max_adapters: int) -> None:
        self.max_adapters = max_adapters
        self.slots: dict[str, int] = {}      # name -> slot (1-based; 0 = null)
        self._free = list(range(max_adapters, 0, -1))
        self.running: dict[str, int] = {}    # name -> active request count
        self.waiting: dict[str, int] = {}
        self.on_evict = None                 # callback(name) when an idle adapter is displaced

    def slot_of(self, name: Optional[str]) -> int:
        if name is None:
            return 0
        return self.slots.get(name, 0)

    def has(self, name: str) -> bool:
        return name in self.slots

    def assign(self, name: str) -> int:
        """Reserve a slot for a new adapter; raises when the bank is full."""
        if name in self.slots:
            return self.slots[name]
        if not self._free:
            # evict an idle adapter if any (simple policy; the reference offloads)
            idle = next((n for n in self.slots
                         if not self.running.get(n) and not self.waiting.get(n)), None)
            if idle is None:
                raise RuntimeError(f"all {self.max_adapters} LoRA slots busy")
            self._free.append(self.slots.pop(idle))
            if self.on_evict is not None:
                self.on_evict(idle)
        slot = self._free.pop()
        self.slots[name] = slot
        return slot

    def remove(self, name: str) -> Optional[int]:
        slot = self.slots.pop(name, None)
        if slot is not None:
            self._free.append(slot)
            self.running.pop(name, None)
            self.waiting.pop(name, None)
        return slot

    # request lifecycle hooks (feed the metrics contract)
    def on_waiting(self, name: Optional[str]) -> None:
        if name:
            self.waiting[name] = self.waiting.get(name, 0) + 1

    def on_running(self, name: Optional[str]) -> None:
        if name:
            if self.waiting.get(name, 0) > 0:
                self.waiting[name] -= 1
            self.running[name] = self.running.get(name, 0) + 1

    def on_finished(self, name: Optional[str]) -> None:
        if name and self.running.get(name, 0) > 0:
            self.running[name] -= 1

    def metrics_info(self) -> dict:
        return {
            "max_lora": self.max_adapters,
            "running_lora_adapters": ",".join(
                sorted(n for n, c in self.running.items() if c > 0)),
            "waiting_lora_adapters": ",".join(
                sorted(n for n, c in self.waiting.items() if c > 0)),
        }
