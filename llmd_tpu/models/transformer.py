"""Functional decoder-only transformer over a paged KV cache.

TPU-first design choices:
- Weights stacked ``[num_layers, ...]`` and the layer stack runs under ``lax.scan`` —
  one trace/compile regardless of depth, XLA pipelines the layers.
- All shapes static: the engine packs work into a fixed flat token budget; page
  tables are fixed-width. No data-dependent control flow.
- **Flat token batch** (vLLM-TPU style): the core takes ``tokens [N]`` holding a
  *mixed* batch — several sequences' prefill chunks plus decode tokens — described by
  ``cu_q_lens``/``num_seqs``. One compiled program serves chunked prefill, batched
  prefill across sequences, and decode; this is what lets the engine pack a full
  ``max-num-batched-tokens`` budget per step instead of one sequence's chunk.
- KV cache layout ``[L*P, page_size, 2*Hk, Dhp]`` — ONE flat page pool with the
  layer folded into the page dimension (layer ``l``'s page ``p`` lives at row
  ``l*P + p``), K/V interleaved per head (K at combined index 2h, V at 2h+1), and
  head_dim padded to the 128-lane tile. This is the layout the TPU
  ragged-paged-attention kernel consumes directly (lane padding is free — XLA's
  HBM tiling would pad the minor dim anyway), and the layer folding is what keeps
  the layer stack scannable: the cache threads through ``lax.scan`` as a *carry*
  updated by in-place scatters, and each layer's attention passes the kernel
  layer-offset page indices into the shared pool. Stacking the cache
  ``[L, P, ...]`` as scan xs/ys instead materializes the full 134 MB layer slice
  twice per layer per step (measured 25-90 ms/step on v5e — the silent dominant
  cost of the round-1 engine).
- bfloat16 everywhere on the matmul path (MXU); fp32 for softmax/rmsnorm accumulation.
- Sharding via logical axis names bound by ``llmd_tpu.parallel.mesh.ShardingRules``:
  heads/mlp → tp, experts → ep, batch → dp (GSPMD inserts the collectives).

Engine-parity note: this plays the role of vLLM's model runner on the reference's TPU
path (vllm `tpu_inference` plugin, docker/common-versions:5-6); attention is the
XLA-reference ragged paged attention below; the Pallas fused kernel lives in
``llmd_tpu.ops.paged_attention`` and is swapped in by the runner on TPU.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from llmd_tpu.models.config import ModelConfig

LANE = 128


def layer_unroll(num_layers: Optional[int] = None) -> int:
    """Effective layer-scan unroll width from ``LLMD_LAYER_UNROLL``.

    Unrolling lets XLA overlap layer N+1's HBM weight stream with layer N's
    compute (a scanned body is one program XLA cannot software-pipeline across
    iterations); decode is weights-BW-bound, so hiding part of the stream
    matters. Cost is compile time. Read at trace time — set before the engine
    builds. The ONE parse used by both the trace site and bench provenance,
    so an artifact can never label an unrolled run as baseline.
    """
    import os

    try:
        n = max(1, int(os.environ.get("LLMD_LAYER_UNROLL", "1")))
    except ValueError:
        n = 1
    return min(n, num_layers) if num_layers else n


def padded_head_dim(head_dim: int) -> int:
    """Head dim as stored in the KV cache: padded up to the 128-lane tile."""
    return max(LANE, ((head_dim + LANE - 1) // LANE) * LANE)


# ---------------------------------------------------------------------------
# Parameter init + logical sharding axes
# ---------------------------------------------------------------------------


def param_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    """Logical axis names per parameter leaf (None entry = replicated axis)."""
    axes: dict[str, Any] = {
        "embed": ("vocab", "embed"),
        "final_norm": ("embed",),
        # stacked per-layer leaves carry a leading 'layers' axis
        "attn_norm": ("layers", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.is_mla:
        # TP shards over heads for W_Q/W_UK/W_UV/W_O; the latent path
        # (W_DKV/W_KR, the per-token shared c_kv) is replicated — it is tiny
        # and every head's shard needs the full latent (DeepSeek TP layout).
        axes |= {
            "mla_wq": ("layers", "embed", "heads", "head_dim"),
            "mla_wdkv": ("layers", "embed", None),
            "mla_wkr": ("layers", "embed", None),
            "mla_kv_norm": ("layers", None),
            "mla_wuk": ("layers", "heads", "head_dim", None),
            "mla_wuv": ("layers", "heads", None, "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
        }
    else:
        axes |= {
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
        }
    if cfg.qk_norm:
        axes |= {"q_norm": ("layers", "head_dim"), "k_norm": ("layers", "head_dim")}
    if cfg.attn_bias:
        axes |= {
            "bq": ("layers", "heads", "head_dim"),
            "bk": ("layers", "kv_heads", "head_dim"),
            "bv": ("layers", "kv_heads", "head_dim"),
            "bo": ("layers", "embed"),
        }
    if cfg.is_moe:
        axes |= {
            "router": ("layers", "embed", "experts"),
            "moe_wi": ("layers", "experts", "embed", "expert_mlp"),
            "moe_wo": ("layers", "experts", "expert_mlp", "embed"),
        }
        if cfg.moe_num_shared_experts:
            axes |= {
                "shared_wi": ("layers", "embed", "mlp"),
                "shared_wo": ("layers", "mlp", "embed"),
            }
    else:
        axes |= {"wi": ("layers", "embed", "mlp"), "wo_mlp": ("layers", "mlp", "embed")}
    if not cfg.tie_embeddings:
        axes["unembed"] = ("embed", "vocab")
    return axes


def init_params(cfg: ModelConfig, key: jax.Array) -> dict[str, jax.Array]:
    """Random-init params (scaled normal); shapes match param_logical_axes."""
    dt = cfg.jax_dtype
    L, D, H, Hk, Dh = cfg.num_layers, cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    F = cfg.intermediate_size
    keys = iter(jax.random.split(key, 20))

    def norm(shape, scale):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dt)

    s = D ** -0.5
    p: dict[str, jax.Array] = {
        "embed": norm((cfg.vocab_size, D), 0.02),
        "final_norm": jnp.ones((D,), dt),
        "attn_norm": jnp.ones((L, D), dt),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.is_mla:
        # DeepSeek-V2/V3 latent attention (deepseek-ai modeling: kv_a_proj
        # W_DKV + decoupled-RoPE key W_KR, up-projections W_UK/W_UV absorbed
        # at inference). No wk/wv — the pool stores [c_kv ; k_rope] once per
        # token, shared by every head.
        r, dr = cfg.mla_kv_lora_rank, cfg.mla_rope_dim
        dn, dv = cfg.mla_qk_nope_dim, cfg.mla_v_head_dim
        p["mla_wq"] = norm((L, D, H, dn + dr), s)
        p["mla_wdkv"] = norm((L, D, r), s)
        p["mla_wkr"] = norm((L, D, dr), s)
        p["mla_kv_norm"] = jnp.ones((L, r), dt)
        p["mla_wuk"] = norm((L, H, dn, r), dn ** -0.5)
        p["mla_wuv"] = norm((L, H, r, dv), r ** -0.5)
        p["wo"] = norm((L, H, dv, D), (H * dv) ** -0.5)
    else:
        p["wq"] = norm((L, D, H, Dh), s)
        p["wk"] = norm((L, D, Hk, Dh), s)
        p["wv"] = norm((L, D, Hk, Dh), s)
        p["wo"] = norm((L, H, Dh, D), (H * Dh) ** -0.5)
    if cfg.qk_norm:
        p["q_norm"] = jnp.ones((L, Dh), dt)
        p["k_norm"] = jnp.ones((L, Dh), dt)
    if cfg.attn_bias:
        p["bq"] = jnp.zeros((L, H, Dh), dt)
        p["bk"] = jnp.zeros((L, Hk, Dh), dt)
        p["bv"] = jnp.zeros((L, Hk, Dh), dt)
        p["bo"] = jnp.zeros((L, D), dt)
    if cfg.is_moe:
        E, Fe = cfg.moe_num_experts, cfg.moe_intermediate_size or F
        p["router"] = norm((L, D, E), s)
        p["moe_wi"] = norm((L, E, D, 2 * Fe), s)
        p["moe_wo"] = norm((L, E, Fe, D), Fe ** -0.5)
        if cfg.moe_num_shared_experts:
            Fs = F * cfg.moe_num_shared_experts
            p["shared_wi"] = norm((L, D, 2 * Fs), s)
            p["shared_wo"] = norm((L, Fs, D), Fs ** -0.5)
    else:
        p["wi"] = norm((L, D, 2 * F), s)  # fused gate+up (SwiGLU)
        p["wo_mlp"] = norm((L, F, D), F ** -0.5)
    if not cfg.tie_embeddings:
        p["unembed"] = norm((D, cfg.vocab_size), s)
    return p


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [..., T, H, Dh]; positions: [..., T]."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    cos, sin = jnp.cos(angles)[..., None, :], jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, wi: jax.Array, wo: jax.Array, mm=None) -> jax.Array:
    """Fused gate/up MLP. ``mm(key, pattern, x)`` overrides the two matmuls
    (the int8 weight-only path injects its scaled-dot here — ONE body for
    both precisions, no drift hazard); wi/wo may be None when mm supplies
    the weights itself."""
    if mm is None:
        def mm(key, pattern, xin, _w={"wi": wi, "wo_mlp": wo}):
            return jnp.einsum(pattern, xin, _w[key])
    gate_up = mm("wi", "...d,df->...f", x)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return mm("wo_mlp", "...f,fd->...d", jax.nn.silu(gate) * up)


def moe_block(
    cfg: ModelConfig,
    x: jax.Array,
    router,
    wi,
    wo,
    eplb: Optional[tuple[jax.Array, jax.Array]] = None,
    matmul_impl=None,
    token_mask: Optional[jax.Array] = None,
    wi_scale: Optional[jax.Array] = None,
    wo_scale: Optional[jax.Array] = None,
    dispatch_impl=None,
    return_dropped: bool = False,
):
    """Top-k routed MoE with capacity-based dispatch (XLA-friendly static shapes).

    ``dispatch_impl(x, idx, topw, valid, wi, wo, wi_scale, wo_scale) -> y``
    replaces the capacity einsums below with the token-sorted drop-free path
    (ops/moe_dispatch; ``EngineConfig.moe_dispatch``). Routing — softmax,
    top-k, renorm, EPLB replica choice — stays HERE either way, so both
    paths see identical routing decisions and the einsum path remains a
    bit-for-bit parity reference. ``return_dropped`` appends a scalar int32
    count of routed-but-dropped copies (always 0 on the sorted path; the
    legacy path drops past capacity C) for the
    ``llmd_tpu:moe_dropped_tokens_total`` surface.

    x: [T, D]. Expert dim is sharded over the `ep` mesh axis; the dispatch/combine
    einsums lower to all-to-all when tokens are dp/sp-sharded — the XLA-native stand-in
    for DeepEP's NVSHMEM all-to-all (reference wide-ep decode.yaml:87-121).

    ``eplb = (replica_slots [E, R], replica_counts [E])`` switches to redundant-expert
    dispatch: ``wi``/``wo`` then hold *physical slot* weights [S, ...] (S >= E, slot
    order = EP-rank placement, see parallel.eplb) and each token spreads across its
    expert's replicas round-robin. ``matmul_impl(xe, w, slot_counts)`` overrides the
    expert GEMMs (Pallas grouped GEMM on TPU — reference DeepGEMM's role, SURVEY §2.5
    N7). Returns (y [T, D], logical expert counts [E] int32).

    ``cfg.moe_dbo`` splits tokens into two independent half-batches so XLA can overlap
    one half's all-to-all with the other's GEMMs (reference --enable-dbo,
    wide-ep decode.yaml:87-121).
    """
    T, D = x.shape
    E, k = cfg.moe_num_experts, cfg.moe_top_k

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32), router.astype(jnp.float32))
    weights = jax.nn.softmax(logits, axis=-1)
    topw, topi = lax.top_k(weights, k)  # [T, k]
    topw = topw / (jnp.sum(topw, axis=-1, keepdims=True) + 1e-9)
    # Padding tokens (prefill chunk tail, idle decode slots) must not consume
    # expert capacity nor pollute the EPLB load stats.
    valid = (
        token_mask.astype(jnp.int32)[:, None]
        if token_mask is not None
        else jnp.ones((T, 1), jnp.int32)
    )  # [T, 1]
    counts = jnp.sum(jax.nn.one_hot(topi, E, dtype=jnp.int32) * valid[..., None], axis=(0, 1))

    if eplb is not None:
        replica_slots, replica_counts = eplb  # [E, R], [E]
        S = wi.shape[0]
        rc = replica_counts[topi]  # [T, k]
        choice = (jnp.arange(T, dtype=jnp.int32)[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]) % rc
        idx = replica_slots[topi, choice]  # [T, k] physical slot ids
    else:
        S, idx = E, topi

    if dispatch_impl is not None:
        def half(x, idx, topw, valid):
            y = dispatch_impl(x, idx, topw, valid, wi, wo, wi_scale, wo_scale)
            return y, jnp.zeros((), jnp.int32)  # drop-free by construction
    else:
        half = None

    def half_einsum(x, idx, topw, valid):
        t = x.shape[0]
        # moe_capacity_factor is a legacy-path-only knob: the sorted path
        # has no capacity C to overflow
        C = max(1, int(t * k / S * cfg.moe_capacity_factor))
        onehot = jax.nn.one_hot(idx, S, dtype=jnp.int32) * valid[..., None]  # [t, k, S]
        flat = onehot.reshape(t * k, S)
        pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(t, k, S)
        keep_i = (pos_in_expert < C).astype(jnp.int32) * onehot  # exact count
        keep = keep_i.astype(x.dtype)
        disp = keep[..., None] * jax.nn.one_hot(pos_in_expert, C, dtype=x.dtype)
        comb = disp * topw[..., None, None].astype(x.dtype)
        disp2 = disp.sum(1)  # [t, S, C]
        comb2 = comb.sum(1)

        xe = jnp.einsum("tec,td->ecd", disp2, x)  # all-to-all in, [S, C, D]
        if matmul_impl is not None and wi_scale is None:
            slot_counts = jnp.sum(disp2, axis=(0, 2)).astype(jnp.int32)  # [S]
            gate_up = matmul_impl(xe, wi, slot_counts)
            gate, up = jnp.split(gate_up, 2, axis=-1)
            ye = matmul_impl(jax.nn.silu(gate) * up, wo, slot_counts)
        else:
            # int8 expert banks: per-expert per-output-channel scales commute
            # out of the dot (see models/quant.py) — [S, 2F] / [S, D]
            gate_up = jnp.einsum("ecd,edf->ecf", xe, wi.astype(x.dtype))
            if wi_scale is not None:
                gate_up = gate_up * wi_scale[:, None, :].astype(x.dtype)
            gate, up = jnp.split(gate_up, 2, axis=-1)
            ye = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up,
                            wo.astype(x.dtype))
            if wo_scale is not None:
                ye = ye * wo_scale[:, None, :].astype(x.dtype)
        y = jnp.einsum("tec,ecd->td", comb2, ye)  # all-to-all back
        kept = jnp.sum(keep_i)  # routed copies that got a capacity slot
        return y, kept

    if half is None:
        half = half_einsum

    if cfg.moe_dbo and T % 2 == 0 and T >= 2:
        h = T // 2
        ya, ka = half(x[:h], idx[:h], topw[:h], valid[:h])
        yb, kb = half(x[h:], idx[h:], topw[h:], valid[h:])
        y, kept = jnp.concatenate([ya, yb]), ka + kb
    else:
        y, kept = half(x, idx, topw, valid)
    if not return_dropped:
        return y, counts
    if dispatch_impl is not None:
        dropped = jnp.zeros((), jnp.int32)
    else:
        dropped = jnp.sum(counts) - kept  # routed minus kept == capacity drops
    return y, counts, dropped


# ---------------------------------------------------------------------------
# Paged KV cache (kernel-native combined layout)
# ---------------------------------------------------------------------------


def init_cache(cfg: ModelConfig, num_pages: int, page_size: int,
               dtype=None, pack: int = 1) -> jax.Array:
    """[L*P, page_size, 2*(Hk/pack), Dhp] flat pool: layer l's page p at row
    l*P + p; K at combined head 2h, V at 2h+1.

    MLA allocates a SINGLE plane — one shared [c_kv ; k_rope] row per token
    (keys and values are the same latent in absorbed attention, so a second
    plane would double KV bytes for nothing; write_kv and the XLA impl detect
    the one-row layout by HkC == 1).

    ``dtype`` overrides the model dtype for the pool — float8_e4m3fn halves
    decode's KV read stream (EngineConfig.kv_cache_dtype="fp8"); the Pallas
    kernel dequantizes pages in VMEM and the XLA fallback upcasts at use.
    ``pack`` > 1 stores that many real KV heads per lane row (ops/packed_kv:
    reclaims the head_dim lane padding; requires Dhp == pack * head_dim).
    """
    if pack > 1:
        assert padded_head_dim(cfg.kv_cache_head_dim) == pack * cfg.kv_cache_head_dim
        assert cfg.kv_cache_heads % pack == 0
    rows = 1 if cfg.is_mla else 2 * (cfg.kv_cache_heads // pack)
    return jnp.zeros(
        (cfg.num_layers * num_pages, page_size, rows,
         padded_head_dim(cfg.kv_cache_head_dim)),
        dtype if dtype is not None else cfg.jax_dtype,
    )


# float8_e4m3fn has no inf: values past ±448 convert to nan, so fp8 cache
# writes clamp first. K/V activations live at O(1)–O(10); the clamp is a
# no-op in practice and fuses into the write's convert.
_FP8_MAX = 448.0


def write_kv(flat_cache: jax.Array, k: jax.Array, v: jax.Array, slots: jax.Array) -> jax.Array:
    """Write new tokens' K/V into flat cache slots (in place under donation).

    flat_cache: [S, 2*Hk, Dhp] (the pool viewed as token slots); k/v:
    [N, Hk, Dhp] (already lane-padded); slots: [N] global slot ids
    (layer_offset + page_id * page_size + offset). Slot -1 marks padding
    (routed out of bounds and dropped by the scatter).
    """
    S, HkC, Dhp = flat_cache.shape
    N, Hk, _ = k.shape
    idx = jnp.where(slots >= 0, slots, S)
    if HkC == 1:
        # single-plane MLA pool: k IS the shared latent; v is ignored
        row = k.astype(jnp.float32) if flat_cache.dtype == jnp.float8_e4m3fn else k
        if flat_cache.dtype == jnp.float8_e4m3fn:
            row = jnp.clip(row, -_FP8_MAX, _FP8_MAX)
        return flat_cache.at[idx].set(row.astype(flat_cache.dtype), mode="drop")
    if HkC < 2 * Hk:
        # packed layout (ops/packed_kv): f real heads per lane row — strip the
        # lane padding and concatenate adjacent heads in slot order
        f = 2 * Hk // HkC
        Dh = Dhp // f
        k = k[:, :, :Dh].reshape(N, Hk // f, Dhp)
        v = v[:, :, :Dh].reshape(N, Hk // f, Dhp)
    # interleave K/V per (packed) head: K even / V odd combined index
    kv = jnp.stack([k, v], axis=2).reshape(N, HkC, Dhp)
    if flat_cache.dtype == jnp.float8_e4m3fn:
        kv = jnp.clip(kv.astype(jnp.float32), -_FP8_MAX, _FP8_MAX)
    kv = kv.astype(flat_cache.dtype)
    return flat_cache.at[idx].set(kv, mode="drop")


def ragged_paged_attention_xla(
    q: jax.Array,  # [N, H, Dhp] flat query tokens (lane-padded)
    layer_cache: jax.Array,  # [P, ps, 2*Hk, Dhp]
    page_tables: jax.Array,  # [B, max_pages] (-1 = unmapped)
    positions: jax.Array,  # [N] global positions (-1 = padding row)
    seq_slots: jax.Array,  # [N] owning batch row per token
    kv_lens: jax.Array,  # [B] tokens resident incl. this step's
    *,
    scale: float,
    cu_q_lens: Optional[jax.Array] = None,  # unused (uniform impl signature)
    num_seqs: Optional[jax.Array] = None,  # unused (uniform impl signature)
    chunk_k: Optional[jax.Array] = None,  # unused (ring-attn impls only)
    chunk_v: Optional[jax.Array] = None,  # unused (ring-attn impls only)
) -> jax.Array:
    """Reference-semantics ragged paged attention (gather + mask), jittable anywhere.

    Each query gathers ONLY its owning sequence's pages via the page table, and
    the token axis runs in fixed-size chunks under ``lax.map`` — peak memory is
    O(chunk * max_pages_per_seq * ps) regardless of pool size OR batch size, so
    the fallback degrades gracefully at serving scale (the pool-wide variant
    allocated multi-TB score tensors at bench shapes; a per-token gather would
    duplicate a prefill's KV once per query token). On TPU the Pallas kernel
    (llmd_tpu.ops.paged_attention) replaces this with per-sequence KV streaming.
    """
    N, H, Dhp = q.shape
    Pn, ps, HkC, _ = layer_cache.shape
    # HkC == 1: single-plane MLA pool — the stored latent serves as BOTH key
    # and value (absorbed attention), i.e. MQA with shared k==v
    single_plane = HkC == 1
    Hk = 1 if single_plane else HkC // 2
    B, maxp = page_tables.shape
    qpk = H // Hk

    b_all = jnp.clip(seq_slots, 0, B - 1)
    C = min(32, N)  # token chunk: bounds the per-step KV gather
    Np = (N + C - 1) // C * C
    qp = jnp.pad(q, ((0, Np - N), (0, 0), (0, 0))).reshape(Np // C, C, H, Dhp)
    posp = jnp.pad(positions, (0, Np - N), constant_values=-1).reshape(Np // C, C)
    bp = jnp.pad(b_all, (0, Np - N)).reshape(Np // C, C)
    key_pos = jnp.arange(maxp * ps, dtype=jnp.int32)[None, :]  # [1, S]

    def one_chunk(args):
        qc, posc, bc = args  # [C, H, Dhp], [C], [C]
        pt = page_tables[bc]  # [C, maxp] owning sequence's pages, in order
        kv = layer_cache[jnp.where(pt >= 0, pt, 0)]  # [C, maxp, ps, 2Hk, Dhp]
        kv = kv.reshape(C, maxp * ps, HkC, Dhp)
        if kv.dtype == jnp.float8_e4m3fn:
            # mirror the Pallas kernel's VMEM dequant: fp8 pages upcast at
            # use; scores already run f32 and p@v must not run in fp8
            kv = kv.astype(qc.dtype)
        if single_plane:
            kc = vc = kv  # [C, S, 1, Dhp] shared latent
        else:
            kc, vc = kv[:, :, 0::2], kv[:, :, 1::2]  # [C, S, Hk, Dhp]
        qg = qc.reshape(C, Hk, qpk, Dhp)
        s = jnp.einsum("nkqd,nskd->nkqs", qg.astype(jnp.float32),
                       kc.astype(jnp.float32)) * scale
        # key j sits at sequence position j (page tables list pages in order)
        mask = (
            (pt[:, key_pos[0] // ps] >= 0)
            & (key_pos <= posc[:, None])
            & (key_pos < kv_lens[bc][:, None])
            & (posc[:, None] >= 0)
        )  # [C, S]
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        # fully masked (padding) rows: softmax is uniform garbage; caller ignores
        return jnp.einsum("nkqs,nskd->nkqd", p.astype(vc.dtype), vc)

    out = lax.map(one_chunk, (qp, posp, bp))  # [Np//C, C, Hk, qpk, Dhp]
    return out.reshape(Np, H, Dhp)[:N]


# ---------------------------------------------------------------------------
# Full forward over the scanned layer stack
# ---------------------------------------------------------------------------


def forward_core(
    cfg: ModelConfig,
    params: dict[str, jax.Array],
    cache: jax.Array,  # [L*P, ps, 2*Hk, Dhp] flat layer-folded pool
    tokens: jax.Array,  # [N] flat mixed batch
    positions: jax.Array,  # [N] (-1 pad)
    seq_slots: jax.Array,  # [N] owning batch row (for page lookup / masks)
    page_tables: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B] cache length AFTER this step's tokens
    cu_q_lens: Optional[jax.Array] = None,  # [B+1] (Pallas kernel path)
    num_seqs: Optional[jax.Array] = None,  # [1] (Pallas kernel path)
    attn_impl=None,
    moe_matmul_impl=None,
    lora_indices: Optional[jax.Array] = None,  # [N] adapter slot per token (0 = none)
    lora_scale: float = 1.0,
    mm_embeds: Optional[jax.Array] = None,  # [N, D] encode-stage rows, row-aligned
    mm_mask: Optional[jax.Array] = None,  # [N] True where tokens[i] is a placeholder
    moe_dispatch_impl=None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Run a flat mixed batch through the model, writing K/V into the paged cache.

    Serves batched/chunked prefill and decode in ONE program: the engine packs
    whatever fits its token budget. Returns (hidden [N, D] final-normed, updated
    cache, expert_counts [L, E], moe_dropped scalar int32 — routed copies the
    legacy capacity path dropped this step, 0 on the sorted path and for dense
    models). Callers unembed whichever rows they need (the
    engine only unembeds each sequence's last row — prefill never pays the full
    [N, vocab] logits matmul).

    ``moe_dispatch_impl`` selects the token-sorted drop-free dispatch
    (ops/moe_dispatch.make_sorted_dispatch); None keeps the capacity-einsum
    legacy path.

    EPLB mode: when ``params`` carries ``eplb_replica_slots``/``eplb_replica_counts``
    (engine-injected, see engine's rebalance path), ``moe_wi``/``moe_wo`` are physical
    slot weights and dispatch spreads tokens over replicas.
    """
    N = tokens.shape[0]
    Ptot, ps, HkC, Dhp = cache.shape
    Dh = cfg.head_dim
    P = Ptot // cfg.num_layers  # pages per layer
    B = page_tables.shape[0]
    if attn_impl is None:
        attn_impl = ragged_paged_attention_xla
    x = params["embed"][tokens].astype(cfg.jax_dtype)  # [N, D]
    if mm_embeds is not None:
        # inject the encode stage's embedding rows at media placeholder
        # positions (E/PD contract: encode workers produce, prefill consumes)
        x = jnp.where(mm_mask[:, None], mm_embeds.astype(x.dtype), x)

    # global slot ids for the new tokens: page_table[seq, pos // ps] * ps + pos % ps
    b = jnp.clip(seq_slots, 0, B - 1)
    pidx = jnp.where(positions >= 0, positions, 0) // ps
    safe_page = jnp.where(page_tables >= 0, page_tables, 0)[b, pidx]
    slots = jnp.where(positions >= 0, safe_page * ps + positions % ps, -1)  # [N]

    def _variants(*keys):
        # a weight-only-quantized model carries <key>_q + <key>_scale instead
        # of <key> (models/quant.py); the scan consumes whichever is present
        out: tuple[str, ...] = ()
        for k in keys:
            out += (k,) if k in params else (k + "_q", k + "_scale")
        return out

    if cfg.is_mla:
        # bias/qk-norm/LoRA-on-attn are GQA-family features; none of the MLA
        # checkpoints combine them (registry enforces the shapes)
        assert not (cfg.qk_norm or cfg.attn_bias), "MLA excludes qk_norm/attn_bias"
        attn_keys = ("mla_wq", "mla_wdkv", "mla_wkr", "mla_kv_norm",
                     "mla_wuk", "mla_wuv") + _variants("wo")
    else:
        attn_keys = _variants("wq", "wk", "wv", "wo")
    stacked_keys = ("attn_norm", "mlp_norm") + attn_keys + (
        ("q_norm", "k_norm") if cfg.qk_norm else ()
    ) + (("bq", "bk", "bv", "bo") if cfg.attn_bias else ()) + (
        ("router",) + _variants("moe_wi", "moe_wo")
        + (_variants("shared_wi", "shared_wo") if cfg.moe_num_shared_experts else ())
        if cfg.is_moe
        else _variants("wi", "wo_mlp")
    )
    if "eplb_replica_slots" in params:
        stacked_keys += ("eplb_replica_slots", "eplb_replica_counts")
    has_lora = "lora_A_wq" in params
    assert not (cfg.is_mla and has_lora), \
        "LoRA adapters are unsupported on MLA models (no adapter hook in the absorbed path)"
    if has_lora:
        from llmd_tpu.models.lora import LORA_TARGETS

        stacked_keys += tuple(f"lora_{ab}_{t}" for t in LORA_TARGETS for ab in "AB")
        if lora_indices is None:
            lora_indices = jnp.zeros((N,), jnp.int32)
    layer_params = {k: params[k] for k in stacked_keys}

    def pad_heads(t):  # [N, h, Dh] → [N, h, Dhp]
        if Dhp == Dh:
            return t
        return jnp.pad(t, ((0, 0), (0, 0), (0, Dhp - Dh)))

    def body(carry, scanned):
        x, flat_cache = carry  # flat_cache: [L*P*ps, 2Hk, Dhp] slot view (in-place carry)
        lp, l = scanned  # per-layer params + layer index

        def _mm(key, pattern, xin):
            """Weight matmul, int8-aware: per-OUTPUT-channel scales commute
            out of the dot (x @ (w*s) == (x @ w) * s), so the dot streams the
            int8 tensor from HBM (XLA fuses the convert into the operand) and
            the scale is one fused elementwise on the output."""
            if key in lp:
                return jnp.einsum(pattern, xin, lp[key])
            y = jnp.einsum(pattern, xin, lp[key + "_q"].astype(xin.dtype))
            return y * lp[key + "_scale"].astype(xin.dtype)

        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        if cfg.is_mla:
            # Absorbed MLA (DeepSeek-V2 §2.1.2 inference form): the pool holds
            # one shared [c_kv ; k_rope] vector per token, queries project into
            # latent space through W_UK, and the whole thing runs as MQA with
            # head_dim = rank + rope_dim over the unmodified paged-attention
            # impl. Scores: q_nope·(W_UK c) + q_rope·k_rope == (W_UK^T q_nope)·c
            # + q_rope·k_rope; values ARE the latents, re-expanded per head
            # through W_UV after the softmax-weighted sum.
            r, dr, dn = cfg.mla_kv_lora_rank, cfg.mla_rope_dim, cfg.mla_qk_nope_dim
            Dkv = r + dr

            def pad_kv(t):  # [N, h, Dkv] → [N, h, Dhp]
                return t if Dhp == Dkv else jnp.pad(
                    t, ((0, 0), (0, 0), (0, Dhp - Dkv)))

            q = jnp.einsum("nd,dhk->nhk", h, lp["mla_wq"])  # [N, H, dn+dr]
            q_rope = rope(q[..., dn:], positions, cfg.rope_theta)
            c = jnp.einsum("nd,dr->nr", h, lp["mla_wdkv"])  # [N, r] latent
            c = rms_norm(c, lp["mla_kv_norm"], cfg.rms_eps)
            kr = rope(jnp.einsum("nd,dk->nk", h, lp["mla_wkr"])[:, None, :],
                      positions, cfg.rope_theta)[:, 0]  # [N, dr] shared key
            q_lat = jnp.einsum("nhk,hkr->nhr", q[..., :dn], lp["mla_wuk"])
            q_attn = pad_kv(jnp.concatenate([q_lat, q_rope], axis=-1))
            k_w = v_w = pad_kv(jnp.concatenate([c, kr], axis=-1)[:, None, :])
            scale = (dn + dr) ** -0.5
        else:
            q = _mm("wq", "nd,dhk->nhk", h)
            k = _mm("wk", "nd,dhk->nhk", h)
            v = _mm("wv", "nd,dhk->nhk", h)
            if cfg.attn_bias:
                q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
            if has_lora:
                from llmd_tpu.models.lora import apply_lora

                Hq, Hkn = cfg.num_heads, cfg.num_kv_heads
                q = q + apply_lora(h, lp["lora_A_wq"], lp["lora_B_wq"], lora_indices,
                                   lora_scale).reshape(N, Hq, Dh)
                k = k + apply_lora(h, lp["lora_A_wk"], lp["lora_B_wk"], lora_indices,
                                   lora_scale).reshape(N, Hkn, Dh)
                v = v + apply_lora(h, lp["lora_A_wv"], lp["lora_B_wv"], lora_indices,
                                   lora_scale).reshape(N, Hkn, Dh)
            if cfg.qk_norm:
                # Per-head RMSNorm over head_dim before RoPE (Qwen3 semantics) — on
                # the FULL projection output incl. bias and LoRA delta, matching the
                # HF/PEFT order (adapters are trained against normalised q/k).
                q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
                k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)
            q_attn, k_w, v_w = pad_heads(q), pad_heads(k), pad_heads(v)
            scale = Dh ** -0.5
        # shared paged plumbing — this layer's slice of the pool: slots/pages
        # shifted by the layer offset, KV written, attention over the pool
        slots_l = jnp.where(slots >= 0, slots + l * (P * ps), -1)
        pt_l = jnp.where(page_tables >= 0, page_tables + l * P, -1)
        flat_cache = write_kv(flat_cache, k_w, v_w, slots_l)
        attn = attn_impl(
            q_attn, flat_cache.reshape(Ptot, ps, HkC, Dhp), pt_l,
            positions, seq_slots, kv_lens,
            cu_q_lens=cu_q_lens, num_seqs=num_seqs, scale=scale,
            chunk_k=k_w, chunk_v=v_w,
        )
        if cfg.is_mla:
            # latent-weighted sum [..., :rank] re-expands per head via W_UV
            o_heads = jnp.einsum("nhr,hrv->nhv",
                                 attn[..., :cfg.mla_kv_lora_rank], lp["mla_wuv"])
            o = _mm("wo", "nhv,hvd->nd", o_heads)
        else:
            attn = attn[..., :Dh]
            o = _mm("wo", "nhk,hkd->nd", attn)
            if cfg.attn_bias:
                o = o + lp["bo"]
            if has_lora:
                attn_flat = attn.reshape(N, cfg.num_heads * Dh)
                o = o + apply_lora(attn_flat, lp["lora_A_wo"], lp["lora_B_wo"],
                                   lora_indices, lora_scale)
        x = x + o

        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        if cfg.is_moe:
            eplb = (
                (lp["eplb_replica_slots"], lp["eplb_replica_counts"])
                if "eplb_replica_slots" in lp
                else None
            )
            quant_moe = "moe_wi_q" in lp  # int8 expert banks: einsum path only
            y, cnt, drop = moe_block(
                cfg, h, lp["router"],
                lp["moe_wi_q" if quant_moe else "moe_wi"],
                lp["moe_wo_q" if quant_moe else "moe_wo"],
                eplb=eplb,
                matmul_impl=None if quant_moe else moe_matmul_impl,
                token_mask=(positions >= 0),
                wi_scale=lp["moe_wi_scale"] if quant_moe else None,
                wo_scale=lp["moe_wo_scale"] if quant_moe else None,
                dispatch_impl=moe_dispatch_impl,
                return_dropped=True,
            )
            if cfg.moe_num_shared_experts:
                if "shared_wi_q" in lp:
                    def _shared_mm(key, pattern, xin):
                        return _mm({"wi": "shared_wi",
                                    "wo_mlp": "shared_wo"}[key], pattern, xin)

                    y = y + swiglu(h, None, None, mm=_shared_mm)
                else:
                    y = y + swiglu(h, lp["shared_wi"], lp["shared_wo"])
        else:
            cnt = jnp.zeros((0,), jnp.int32)
            drop = jnp.zeros((), jnp.int32)
            y = swiglu(h, None, None, mm=_mm) if "wi_q" in lp else swiglu(
                h, lp["wi"], lp["wo_mlp"])
        x = x + y
        return (x, flat_cache), (cnt, drop)

    (x, flat_cache), (expert_counts, dropped) = lax.scan(
        body,
        (x, cache.reshape(Ptot * ps, HkC, Dhp)),
        (layer_params, jnp.arange(cfg.num_layers, dtype=jnp.int32)),
        unroll=layer_unroll(cfg.num_layers),
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return x, flat_cache.reshape(Ptot, ps, HkC, Dhp), expert_counts, dropped.sum()


def unembed(cfg: ModelConfig, params: dict[str, jax.Array], hidden: jax.Array) -> jax.Array:
    """hidden [..., D] → logits [..., vocab] (fp32)."""
    if "unembed_q" in params:  # weight-only int8 (models/quant.py)
        logits = jnp.einsum("...d,dv->...v", hidden.astype(jnp.float32),
                            params["unembed_q"].astype(jnp.float32))
        return logits * params["unembed_scale"].astype(jnp.float32)
    w = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    return jnp.einsum("...d,dv->...v", hidden.astype(jnp.float32), w.astype(jnp.float32))


def forward(
    cfg: ModelConfig,
    params: dict[str, jax.Array],
    cache: jax.Array,  # [L, P, ps, 2*Hk, Dhp]
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T] (-1 pad)
    page_tables: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B] cache length AFTER this step's tokens
    moe_matmul_impl=None,
    lora_indices: Optional[jax.Array] = None,  # [B] adapter slot per row (0 = none)
    lora_scale: float = 1.0,
    with_hidden: bool = False,
    moe_dispatch_impl=None,
) -> tuple[jax.Array, ...]:
    """[B, T]-shaped convenience wrapper over ``forward_core`` (tests, entrypoints).

    Flattens row-major and ALWAYS uses the XLA-reference attention — the [B, T]
    padded layout is incompatible with the Pallas kernel's cu_q_lens contract, so
    no attn_impl override is accepted (engine callers use forward_core directly).
    Returns full logits [B, T, vocab] like the classic contract.
    """
    B, T = tokens.shape
    seq_slots = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
    lora_tok = jnp.repeat(lora_indices, T) if lora_indices is not None else None
    hidden, new_cache, counts, _dropped = forward_core(
        cfg, params, cache, tokens.reshape(-1), positions.reshape(-1), seq_slots,
        page_tables, kv_lens, attn_impl=None, moe_matmul_impl=moe_matmul_impl,
        lora_indices=lora_tok, lora_scale=lora_scale,
        moe_dispatch_impl=moe_dispatch_impl,
    )
    logits = unembed(cfg, params, hidden).reshape(B, T, -1)
    if with_hidden:
        return logits, new_cache, counts, hidden.reshape(B, T, -1)
    return logits, new_cache, counts
