"""Functional decoder-only transformer over a paged KV cache.

TPU-first design choices:
- Weights stacked ``[num_layers, ...]`` and the layer stack runs under ``lax.scan`` —
  one trace/compile regardless of depth, XLA pipelines the layers.
- All shapes static: chunked prefill processes fixed-size chunks, decode processes a
  fixed slot batch; page tables are fixed-width. No data-dependent control flow.
- bfloat16 everywhere on the matmul path (MXU); fp32 for softmax/rmsnorm accumulation.
- Sharding via logical axis names bound by ``llmd_tpu.parallel.mesh.ShardingRules``:
  heads/mlp → tp, experts → ep, batch → dp (GSPMD inserts the collectives).

Engine-parity note: this plays the role of vLLM's model runner on the reference's TPU
path (vllm `tpu_inference` plugin, docker/common-versions:5-6); attention is the
reference-semantics paged attention; the Pallas fused kernel lives in
``llmd_tpu.ops.paged_attention`` and is swapped in by the runner on TPU.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from llmd_tpu.models.config import ModelConfig

# ---------------------------------------------------------------------------
# Parameter init + logical sharding axes
# ---------------------------------------------------------------------------


def param_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    """Logical axis names per parameter leaf (None entry = replicated axis)."""
    axes: dict[str, Any] = {
        "embed": ("vocab", "embed"),
        "final_norm": ("embed",),
        # stacked per-layer leaves carry a leading 'layers' axis
        "attn_norm": ("layers", "embed"),
        "mlp_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
    }
    if cfg.is_moe:
        axes |= {
            "router": ("layers", "embed", "experts"),
            "moe_wi": ("layers", "experts", "embed", "expert_mlp"),
            "moe_wo": ("layers", "experts", "expert_mlp", "embed"),
        }
        if cfg.moe_num_shared_experts:
            axes |= {
                "shared_wi": ("layers", "embed", "mlp"),
                "shared_wo": ("layers", "mlp", "embed"),
            }
    else:
        axes |= {"wi": ("layers", "embed", "mlp"), "wo_mlp": ("layers", "mlp", "embed")}
    if not cfg.tie_embeddings:
        axes["unembed"] = ("embed", "vocab")
    return axes


def init_params(cfg: ModelConfig, key: jax.Array) -> dict[str, jax.Array]:
    """Random-init params (scaled normal); shapes match param_logical_axes."""
    dt = cfg.jax_dtype
    L, D, H, Hk, Dh = cfg.num_layers, cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    F = cfg.intermediate_size
    keys = iter(jax.random.split(key, 20))

    def norm(shape, scale):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(dt)

    s = D ** -0.5
    p: dict[str, jax.Array] = {
        "embed": norm((cfg.vocab_size, D), 0.02),
        "final_norm": jnp.ones((D,), dt),
        "attn_norm": jnp.ones((L, D), dt),
        "mlp_norm": jnp.ones((L, D), dt),
        "wq": norm((L, D, H, Dh), s),
        "wk": norm((L, D, Hk, Dh), s),
        "wv": norm((L, D, Hk, Dh), s),
        "wo": norm((L, H, Dh, D), (H * Dh) ** -0.5),
    }
    if cfg.is_moe:
        E, Fe = cfg.moe_num_experts, cfg.moe_intermediate_size or F
        p["router"] = norm((L, D, E), s)
        p["moe_wi"] = norm((L, E, D, 2 * Fe), s)
        p["moe_wo"] = norm((L, E, Fe, D), Fe ** -0.5)
        if cfg.moe_num_shared_experts:
            Fs = F * cfg.moe_num_shared_experts
            p["shared_wi"] = norm((L, D, 2 * Fs), s)
            p["shared_wo"] = norm((L, Fs, D), Fs ** -0.5)
    else:
        p["wi"] = norm((L, D, 2 * F), s)  # fused gate+up (SwiGLU)
        p["wo_mlp"] = norm((L, F, D), F ** -0.5)
    if not cfg.tie_embeddings:
        p["unembed"] = norm((D, cfg.vocab_size), s)
    return p


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [..., T, H, Dh]; positions: [..., T]."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    cos, sin = jnp.cos(angles)[..., None, :], jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, wi: jax.Array, wo: jax.Array) -> jax.Array:
    gate_up = jnp.einsum("...d,df->...f", x, wi)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return jnp.einsum("...f,fd->...d", jax.nn.silu(gate) * up, wo)


def moe_block(
    cfg: ModelConfig,
    x: jax.Array,
    router,
    wi,
    wo,
    eplb: Optional[tuple[jax.Array, jax.Array]] = None,
    matmul_impl=None,
    token_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k routed MoE with capacity-based dispatch (XLA-friendly static shapes).

    x: [T, D]. Expert dim is sharded over the `ep` mesh axis; the dispatch/combine
    einsums lower to all-to-all when tokens are dp/sp-sharded — the XLA-native stand-in
    for DeepEP's NVSHMEM all-to-all (reference wide-ep decode.yaml:87-121).

    ``eplb = (replica_slots [E, R], replica_counts [E])`` switches to redundant-expert
    dispatch: ``wi``/``wo`` then hold *physical slot* weights [S, ...] (S >= E, slot
    order = EP-rank placement, see parallel.eplb) and each token spreads across its
    expert's replicas round-robin. ``matmul_impl(xe, w, slot_counts)`` overrides the
    expert GEMMs (Pallas grouped GEMM on TPU — reference DeepGEMM's role, SURVEY §2.5
    N7). Returns (y [T, D], logical expert counts [E] int32).

    ``cfg.moe_dbo`` splits tokens into two independent half-batches so XLA can overlap
    one half's all-to-all with the other's GEMMs (reference --enable-dbo,
    wide-ep decode.yaml:87-121).
    """
    T, D = x.shape
    E, k = cfg.moe_num_experts, cfg.moe_top_k

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32), router.astype(jnp.float32))
    weights = jax.nn.softmax(logits, axis=-1)
    topw, topi = lax.top_k(weights, k)  # [T, k]
    topw = topw / (jnp.sum(topw, axis=-1, keepdims=True) + 1e-9)
    # Padding tokens (prefill chunk tail, idle decode slots) must not consume
    # expert capacity nor pollute the EPLB load stats.
    valid = (
        token_mask.astype(jnp.int32)[:, None]
        if token_mask is not None
        else jnp.ones((T, 1), jnp.int32)
    )  # [T, 1]
    counts = jnp.sum(jax.nn.one_hot(topi, E, dtype=jnp.int32) * valid[..., None], axis=(0, 1))

    if eplb is not None:
        replica_slots, replica_counts = eplb  # [E, R], [E]
        S = wi.shape[0]
        rc = replica_counts[topi]  # [T, k]
        choice = (jnp.arange(T, dtype=jnp.int32)[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]) % rc
        idx = replica_slots[topi, choice]  # [T, k] physical slot ids
    else:
        S, idx = E, topi

    def half(x, idx, topw, valid):
        t = x.shape[0]
        C = max(1, int(t * k / S * cfg.moe_capacity_factor))
        onehot = jax.nn.one_hot(idx, S, dtype=jnp.int32) * valid[..., None]  # [t, k, S]
        flat = onehot.reshape(t * k, S)
        pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(t, k, S)
        keep = (pos_in_expert < C).astype(x.dtype) * onehot.astype(x.dtype)
        disp = keep[..., None] * jax.nn.one_hot(pos_in_expert, C, dtype=x.dtype)
        comb = disp * topw[..., None, None].astype(x.dtype)
        disp2 = disp.sum(1)  # [t, S, C]
        comb2 = comb.sum(1)

        xe = jnp.einsum("tec,td->ecd", disp2, x)  # all-to-all in, [S, C, D]
        if matmul_impl is not None:
            slot_counts = jnp.sum(disp2, axis=(0, 2)).astype(jnp.int32)  # [S]
            gate_up = matmul_impl(xe, wi, slot_counts)
            gate, up = jnp.split(gate_up, 2, axis=-1)
            ye = matmul_impl(jax.nn.silu(gate) * up, wo, slot_counts)
        else:
            gate_up = jnp.einsum("ecd,edf->ecf", xe, wi)
            gate, up = jnp.split(gate_up, 2, axis=-1)
            ye = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up, wo)
        return jnp.einsum("tec,ecd->td", comb2, ye)  # all-to-all back

    if cfg.moe_dbo and T % 2 == 0 and T >= 2:
        h = T // 2
        y = jnp.concatenate([
            half(x[:h], idx[:h], topw[:h], valid[:h]),
            half(x[h:], idx[h:], topw[h:], valid[h:]),
        ])
    else:
        y = half(x, idx, topw, valid)
    return y, counts


# ---------------------------------------------------------------------------
# Paged attention (reference semantics; Pallas kernel swapped in by the runner)
# ---------------------------------------------------------------------------


class PagedKVLayout(NamedTuple):
    """cache: [L, 2, num_pages, page_size, kv_heads, head_dim] (k=0, v=1)."""

    num_pages: int
    page_size: int


def write_kv(layer_cache: jax.Array, k: jax.Array, v: jax.Array, slots: jax.Array) -> jax.Array:
    """Write new tokens' K/V into flat page slots.

    layer_cache: [2, P, ps, Hk, Dh]; k/v: [T, Hk, Dh]; slots: [T] global slot ids
    (page_id * page_size + offset). Slot -1 marks padding (dropped via clamp+where).
    """
    two, Pn, ps, Hk, Dh = layer_cache.shape
    flat = layer_cache.reshape(2, Pn * ps, Hk, Dh)
    # Padding tokens (slot -1) are routed out of bounds and dropped by the scatter —
    # never remap them to a real slot: a duplicate index with a real write has
    # undefined winner ordering.
    idx = jnp.where(slots >= 0, slots, Pn * ps)
    kv = jnp.stack([k, v]).astype(flat.dtype)  # [2, T, Hk, Dh]
    flat = flat.at[:, idx].set(kv, mode="drop")
    return flat.reshape(2, Pn, ps, Hk, Dh)


def paged_attention(
    q: jax.Array,  # [B, T, H, Dh]
    layer_cache: jax.Array,  # [2, P, ps, Hk, Dh]
    page_tables: jax.Array,  # [B, max_pages]
    q_positions: jax.Array,  # [B, T] global positions of queries (-1 pad)
    kv_lens: jax.Array,  # [B] total tokens in cache per seq (incl. new)
) -> jax.Array:
    """Reference-semantics ragged paged attention (gather + mask).

    Every query attends to its sequence's cache slots with causal masking by global
    position. Static shapes: S = max_pages * page_size keys are gathered and masked.
    """
    B, T, H, Dh = q.shape
    _, Pn, ps, Hk, _ = layer_cache.shape
    S = page_tables.shape[1] * ps
    kc, vc = layer_cache[0], layer_cache[1]
    safe_pages = jnp.where(page_tables >= 0, page_tables, 0)
    k = kc[safe_pages].reshape(B, S, Hk, Dh)  # [B, S, Hk, Dh]
    v = vc[safe_pages].reshape(B, S, Hk, Dh)

    qpk = H // Hk
    qg = q.reshape(B, T, Hk, qpk, Dh)
    scores = jnp.einsum("bthqd,bshd->bhqts", qg.astype(jnp.float32), k.astype(jnp.float32))
    scores *= Dh ** -0.5

    key_pos = jnp.arange(S)[None, :]  # [1, S]
    valid_key = key_pos < kv_lens[:, None]  # [B, S]
    causal = key_pos[:, None, :] <= q_positions[..., None]  # [B, T, S]
    mask = (valid_key[:, None, :] & causal & (q_positions[..., None] >= 0))  # [B, T, S]
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqts,bshd->bthqd", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, Dh)


# ---------------------------------------------------------------------------
# Full forward over the scanned layer stack
# ---------------------------------------------------------------------------


def forward(
    cfg: ModelConfig,
    params: dict[str, jax.Array],
    cache: jax.Array,  # [L, 2, P, ps, Hk, Dh]
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T] (-1 pad)
    page_tables: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B] cache length AFTER this step's tokens
    attn_impl=paged_attention,
    moe_matmul_impl=None,
    lora_indices: Optional[jax.Array] = None,  # [B] adapter slot per row (0 = none)
    lora_scale: float = 1.0,
    with_hidden: bool = False,  # append final-norm hidden states (embeddings path)
) -> tuple[jax.Array, ...]:
    """Run tokens through the model, writing K/V into the paged cache.

    Serves both chunked prefill (T = chunk) and decode (T = 1): the engine packs
    whatever fits. Returns (logits [B, T, vocab], updated cache, expert_counts)
    where expert_counts is the per-layer routed-token stat [L, E] int32 feeding
    the EPLB load tracker ([L, 0] for dense models — callers ignore it freely).

    EPLB mode: when ``params`` carries ``eplb_replica_slots``/``eplb_replica_counts``
    (engine-injected, see engine's rebalance path), ``moe_wi``/``moe_wo`` are physical
    slot weights and dispatch spreads tokens over replicas.
    """
    B, T = tokens.shape
    ps = cache.shape[3]
    x = params["embed"][tokens].astype(cfg.jax_dtype)  # [B, T, D]

    # global slot ids for the new tokens: page_table[pos // ps] * ps + pos % ps
    pidx = jnp.where(positions >= 0, positions, 0) // ps
    safe_page = jnp.take_along_axis(jnp.where(page_tables >= 0, page_tables, 0), pidx, axis=1)
    slots = jnp.where(positions >= 0, safe_page * ps + positions % ps, -1)  # [B, T]
    flat_slots = slots.reshape(B * T)

    stacked_keys = ("attn_norm", "mlp_norm", "wq", "wk", "wv", "wo") + (
        ("router", "moe_wi", "moe_wo") + (("shared_wi", "shared_wo") if cfg.moe_num_shared_experts else ())
        if cfg.is_moe
        else ("wi", "wo_mlp")
    )
    if "eplb_replica_slots" in params:
        stacked_keys += ("eplb_replica_slots", "eplb_replica_counts")
    has_lora = "lora_A_wq" in params
    if has_lora:
        from llmd_tpu.models.lora import LORA_TARGETS

        stacked_keys += tuple(f"lora_{ab}_{t}" for t in LORA_TARGETS for ab in "AB")
        if lora_indices is None:
            lora_indices = jnp.zeros((B,), jnp.int32)
    layer_params = {k: params[k] for k in stacked_keys}

    def body(carry, scanned):
        x, _ = carry
        lp, cache_l = scanned  # per-layer params + this layer's cache [2, P, ps, Hk, Dh]
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = jnp.einsum("btd,dhk->bthk", h, lp["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, lp["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, lp["wv"])
        if has_lora:
            from llmd_tpu.models.lora import apply_lora

            Hq, Hk, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
            q = q + apply_lora(h, lp["lora_A_wq"], lp["lora_B_wq"], lora_indices,
                               lora_scale).reshape(B, T, Hq, Dh)
            k = k + apply_lora(h, lp["lora_A_wk"], lp["lora_B_wk"], lora_indices,
                               lora_scale).reshape(B, T, Hk, Dh)
            v = v + apply_lora(h, lp["lora_A_wv"], lp["lora_B_wv"], lora_indices,
                               lora_scale).reshape(B, T, Hk, Dh)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        cache_l = write_kv(cache_l, k.reshape(B * T, cfg.num_kv_heads, cfg.head_dim),
                           v.reshape(B * T, cfg.num_kv_heads, cfg.head_dim), flat_slots)
        attn = attn_impl(q, cache_l, page_tables, positions, kv_lens)
        o = jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        if has_lora:
            attn_flat = attn.reshape(B, T, cfg.num_heads * cfg.head_dim)
            o = o + apply_lora(attn_flat, lp["lora_A_wo"], lp["lora_B_wo"],
                               lora_indices, lora_scale)
        x = x + o

        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        if cfg.is_moe:
            eplb = (
                (lp["eplb_replica_slots"], lp["eplb_replica_counts"])
                if "eplb_replica_slots" in lp
                else None
            )
            y, cnt = moe_block(
                cfg, h.reshape(B * T, -1), lp["router"], lp["moe_wi"], lp["moe_wo"],
                eplb=eplb, matmul_impl=moe_matmul_impl,
                token_mask=(positions >= 0).reshape(B * T),
            )
            y = y.reshape(B, T, -1)
            if cfg.moe_num_shared_experts:
                y = y + swiglu(h, lp["shared_wi"], lp["shared_wo"])
        else:
            cnt = jnp.zeros((0,), jnp.int32)
            y = swiglu(h, lp["wi"], lp["wo_mlp"])
        x = x + y
        return (x, 0), (cache_l, cnt)

    (x, _), (new_cache, expert_counts) = lax.scan(body, (x, 0), (layer_params, cache))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = jnp.einsum("btd,dv->btv", x.astype(jnp.float32), unembed.astype(jnp.float32))
    if with_hidden:
        return logits, new_cache, expert_counts, x
    return logits, new_cache, expert_counts


def init_cache(cfg: ModelConfig, num_pages: int, page_size: int) -> jax.Array:
    return jnp.zeros(
        (cfg.num_layers, 2, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim),
        cfg.jax_dtype,
    )
