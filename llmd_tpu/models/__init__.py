"""Model families: functional JAX decoder-only transformers (dense + MoE).

Covers the architectures the reference serves through vLLM (Llama/Qwen dense,
Qwen/DeepSeek MoE — guides/* model lists) with one configurable stack: RoPE, GQA,
RMSNorm, SwiGLU, optional top-k routed MoE with shared experts. Weights are stacked
[L, ...] and the stack runs under lax.scan so compile time is depth-independent.
"""

from llmd_tpu.models.config import ModelConfig  # noqa: F401
from llmd_tpu.models.registry import get_model_config, MODEL_REGISTRY  # noqa: F401
