"""Model families: functional JAX decoder-only transformers (dense + MoE).

Covers the architectures the reference serves through vLLM (Llama/Qwen dense,
Qwen/DeepSeek MoE — guides/* model lists) with one configurable stack: RoPE, GQA,
RMSNorm, SwiGLU, optional top-k routed MoE with shared experts. Weights are stacked
[L, ...] and the stack runs under lax.scan so compile time is depth-independent.
"""

from llmd_tpu.models.config import ModelConfig  # noqa: F401
from llmd_tpu.models.registry import get_model_config, MODEL_REGISTRY  # noqa: F401


def resolve_model(name_or_path: str, dtype: str = "bfloat16"):
    """(ModelConfig, params|None) from a registry name OR an HF checkpoint dir.

    Registry names return ``params=None`` (caller random-inits — CI shapes);
    an HF dir loads real weights through ``llmd_tpu.models.hf_loader``.
    """
    from llmd_tpu.models.hf_loader import is_hf_checkpoint, load_model

    if is_hf_checkpoint(name_or_path):
        return load_model(name_or_path, dtype=dtype)
    return get_model_config(name_or_path), None
