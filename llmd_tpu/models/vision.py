"""Vision tower for multimodal serving: pixels → mm embedding tokens.

The encode (E) stage of the reference's E/PD / E/P/D topologies
(`guides/multimodal-serving/e-disaggregation/README.md`): media is converted to
a FIXED number of embedding rows (``cfg.mm_tokens``) that prefill injects at
placeholder positions alongside text tokens. TPU-first choices:

- one jitted program per image: patchify (a reshaped matmul — MXU), add learned
  position embeddings, run a small pre-norm transformer, mean-pool patches into
  ``mm_tokens`` rows, project to the language ``hidden_size``;
- all shapes static: images are bilinearly resized to ``vision_image_size``²
  before entering jit, so any input resolution compiles exactly once;
- encode workers batch independent media items along a leading axis (the
  "parallelized across entries" property of the reference's encode workers —
  one program, N items).

Media bytes → pixels: raw RGB/grayscale arrays are accepted directly; arbitrary
byte payloads (we ship no image codec) map deterministically onto pseudo-pixels
via a seeded hash so identity, caching, and parity tests work end to end on any
payload. Real deployments plug a decoder in front; the serving contract (bytes →
[mm_tokens, hidden] rows keyed by content hash) is unchanged.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from llmd_tpu.models.config import ModelConfig


def vision_param_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    """Sharding axes for the vision tower (replicated by default — it is tiny
    next to the language stack; encode workers scale out, not shard)."""
    return {
        "v_patch": (None, "embed"),
        "v_pos": (None, "embed"),
        "v_norm1": ("layers", "embed"),
        "v_qkv": ("layers", "embed", None),
        "v_out": ("layers", "embed", "embed"),
        "v_norm2": ("layers", "embed"),
        "v_mlp_in": ("layers", "embed", "mlp"),
        "v_mlp_out": ("layers", "mlp", "embed"),
        "v_final_norm": ("embed",),
        "v_proj": ("embed", None),
    }


def init_vision_params(cfg: ModelConfig, key: jax.Array) -> dict[str, jax.Array]:
    D = cfg.vision_hidden
    L = cfg.vision_layers
    P = cfg.vision_patch
    n_patches = (cfg.vision_image_size // P) ** 2
    patch_dim = P * P * 3
    F = 4 * D
    dt = cfg.jax_dtype
    ks = iter(jax.random.split(key, 12))

    def norm(shape, scale):
        return (jax.random.normal(next(ks), shape, jnp.float32) * scale).astype(dt)

    return {
        "v_patch": norm((patch_dim, D), patch_dim ** -0.5),
        "v_pos": norm((n_patches, D), 0.02),
        "v_norm1": jnp.ones((L, D), dt),
        "v_qkv": norm((L, D, 3 * D), D ** -0.5),
        "v_out": norm((L, D, D), D ** -0.5),
        "v_norm2": jnp.ones((L, D), dt),
        "v_mlp_in": norm((L, D, F), D ** -0.5),
        "v_mlp_out": norm((L, F, D), F ** -0.5),
        "v_final_norm": jnp.ones((D,), dt),
        "v_proj": norm((D, cfg.hidden_size), D ** -0.5),
    }


def _rms(x, w, eps=1e-6):
    v = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(v + eps)).astype(x.dtype) * w


def encode_images(cfg: ModelConfig, params: dict[str, jax.Array],
                  pixels: jax.Array) -> jax.Array:
    """[N, S, S, 3] float pixels in [0, 1] → [N, mm_tokens, hidden_size].

    Jittable; N is the encode-worker batch of independent media items.
    """
    N = pixels.shape[0]
    P = cfg.vision_patch
    S = cfg.vision_image_size
    D = cfg.vision_hidden
    H = cfg.vision_heads
    hd = D // H
    n_patches = (S // P) ** 2
    # patchify: [N, S/P, P, S/P, P, 3] → [N, n_patches, P*P*3]
    x = pixels.astype(cfg.jax_dtype).reshape(N, S // P, P, S // P, P, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(N, n_patches, P * P * 3)
    x = x @ params["v_patch"] + params["v_pos"]

    def layer(x, lp):
        h = _rms(x, lp["v_norm1"])
        qkv = h @ lp["v_qkv"]  # [N, T, 3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(N, n_patches, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(N, n_patches, H, hd).transpose(0, 2, 1, 3)
        v = v.reshape(N, n_patches, H, hd).transpose(0, 2, 1, 3)
        s = jnp.einsum("nhqd,nhkd->nhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * hd ** -0.5
        a = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("nhqk,nhkd->nhqd", a, v).transpose(0, 2, 1, 3).reshape(N, n_patches, D)
        x = x + o @ lp["v_out"]
        h = _rms(x, lp["v_norm2"])
        return x + jax.nn.gelu(h @ lp["v_mlp_in"]) @ lp["v_mlp_out"], None

    stacked = {k: params[k] for k in
               ("v_norm1", "v_qkv", "v_out", "v_norm2", "v_mlp_in", "v_mlp_out")}
    x, _ = jax.lax.scan(lambda c, lp: layer(c, lp), x, stacked)
    x = _rms(x, params["v_final_norm"])
    # pool patch groups into the fixed mm_tokens rows, then project to the LM width
    x = x.reshape(N, cfg.mm_tokens, n_patches // cfg.mm_tokens, D).mean(axis=2)
    return (x @ params["v_proj"]).astype(cfg.jax_dtype)  # [N, mm_tokens, hidden]


# ---------------------------------------------------------------------------
# Media bytes → pixels + identity
# ---------------------------------------------------------------------------


def mm_content_hash(data: bytes) -> bytes:
    """Stable media identity: folded into block keys + used as the cache key
    between encode workers and P/D engines."""
    return hashlib.sha256(data).digest()[:16]


def bytes_to_pixels(cfg: ModelConfig, data: bytes) -> np.ndarray:
    """Deterministic bytes → [S, S, 3] float32 pixels in [0, 1].

    A real decoder (JPEG/PNG) slots in here; absent one in this image, the
    payload seeds a generator so distinct media map to distinct pixel tensors
    (and identical media always encode identically — required for caching)."""
    S = cfg.vision_image_size
    seed = int.from_bytes(hashlib.sha256(data).digest()[:8], "little", signed=False)
    rng = np.random.default_rng(seed)
    return rng.random((S, S, 3), dtype=np.float32)
