"""Model architecture config."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny"
    vocab_size: int = 288  # byte-level tokenizer (256 bytes + specials), padded to tile
    hidden_size: int = 128
    intermediate_size: int = 384
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int = 32
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    max_position: int = 32768
    tie_embeddings: bool = True
    dtype: str = "bfloat16"
    # Per-head RMSNorm on q/k before RoPE (Qwen3-family checkpoints).
    qk_norm: bool = False
    # Bias terms on the q/k/v projections (Qwen2-family checkpoints).
    attn_bias: bool = False
    # MoE (0 experts = dense). All layers share the same shape so the stack scans.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_intermediate_size: int = 0
    moe_num_shared_experts: int = 0
    moe_capacity_factor: float = 1.25
    # Dual-batch overlap: split MoE tokens into two independent half-batches so XLA
    # overlaps one half's all-to-all with the other's expert GEMMs (--enable-dbo).
    moe_dbo: bool = False
    # Multimodal (vision tower): 0 mm_tokens = text-only. Each media item
    # contributes exactly mm_tokens placeholder positions (id mm_placeholder_id)
    # whose embeddings are injected from the encode stage — the E/PD contract
    # (guides/multimodal-serving/e-disaggregation/README.md: encode workers
    # produce embeddings consumed by prefill/decode alongside text tokens).
    mm_tokens: int = 0
    mm_placeholder_id: int = 0
    vision_patch: int = 8  # square patch edge (pixels)
    vision_image_size: int = 32  # inputs resized/cropped to this square edge
    vision_layers: int = 0
    vision_hidden: int = 0
    vision_heads: int = 4
    # Multi-head latent attention (DeepSeek-V2/V3 family — the architecture of
    # the reference's wide-EP north-star benchmarks, guides/wide-ep-lws). KV is
    # compressed to a shared per-token latent c_kv [mla_kv_lora_rank] plus a
    # decoupled RoPE key [mla_rope_dim]; attention runs ABSORBED (q projected
    # into latent space through W_UK, output re-expanded through W_UV), which
    # makes it exactly MQA with head_dim = rank + rope_dim over the paged pool
    # — per-token KV bytes shrink ~(2*Hk*Dh)/(rank+rope) vs GQA.
    # 0 = standard GQA attention.
    mla_kv_lora_rank: int = 0
    mla_rope_dim: int = 0
    mla_qk_nope_dim: int = 0  # per-head non-RoPE q/k dim (score dot in latent space)
    mla_v_head_dim: int = 0  # per-head value dim after W_UV re-expansion

    @property
    def has_vision(self) -> bool:
        return self.mm_tokens > 0 and self.vision_layers > 0

    @property
    def jax_dtype(self):
        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[self.dtype]

    @property
    def is_moe(self) -> bool:
        return self.moe_num_experts > 0

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def is_mla(self) -> bool:
        return self.mla_kv_lora_rank > 0

    @property
    def kv_cache_heads(self) -> int:
        """KV heads as stored in the paged pool (1 for MLA's shared latent)."""
        return 1 if self.is_mla else self.num_kv_heads

    @property
    def kv_cache_head_dim(self) -> int:
        """Per-token per-head KV width in the pool (latent + rope key for MLA)."""
        return (self.mla_kv_lora_rank + self.mla_rope_dim) if self.is_mla \
            else self.head_dim
