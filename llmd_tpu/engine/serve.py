"""CLI: python -m llmd_tpu.engine.serve --model tiny --port 8000 [--cpu] ...

The vLLM-serve analogue for the TPU engine (flag names mirror the reference's
modelserver args where they exist, e.g. --block-size / --kv-events-port).
"""

from __future__ import annotations

import argparse
import asyncio
import os


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny",
                    help="registry name (llmd_tpu.models.MODEL_REGISTRY) or a local "
                         "HF checkpoint dir (config.json + safetensors)")
    ap.add_argument("--served-model-name", default=None)
    # env-default ports: the container image / manifests configure pods via
    # LLMD_TPU_* (deploy/ENV_VARS.md contract); flags still win when passed
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("LLMD_TPU_PORT", "8000")))
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=512)
    ap.add_argument("--max-model-len", type=int, default=2048)
    ap.add_argument("--max-batch-size", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=128)
    ap.add_argument("--decode-steps", type=int, default=4)
    _env_kve = os.environ.get("LLMD_TPU_KV_EVENTS_PORT")
    ap.add_argument("--kv-events-port", type=int,
                    default=int(_env_kve) if _env_kve else None,
                    help="bind ZMQ KV-event PUB here (pod-discovery mode)")
    _env_kvt = os.environ.get("LLMD_TPU_KV_TRANSFER_PORT")
    ap.add_argument("--kv-transfer-port", type=int,
                    default=int(_env_kvt) if _env_kvt else None,
                    help="bind the P/D KV-transfer side channel here (0 = random; "
                         "TPU_KV_TRANSFER_PORT analogue, reference default 9100)")
    ap.add_argument("--advertise-host", default=None,
                    help="routable host for kv_transfer_params (defaults to --host "
                         "unless that is a bind-any address)")
    ap.add_argument("--tokenizer", default=None, help="local HF tokenizer dir")
    ap.add_argument("--role", default="both", choices=["both", "prefill", "decode"])
    ap.add_argument("--quantize", default=None, choices=["int8"],
                    help="weight-only quantization: halves decode's HBM "
                         "weight traffic (models/quant.py)")
    ap.add_argument("--kv-cache-dtype", default=None, choices=["fp8"],
                    help="fp8 KV pool: halves decode's per-step KV read "
                         "stream (the vLLM --kv-cache-dtype role)")
    ap.add_argument("--kv-layout", default="auto",
                    choices=["auto", "packed", "padded"],
                    help="KV pool lane layout (ops/packed_kv): auto packs "
                         "head_dim-64 models' KV pairs per 128-lane row")
    ap.add_argument("--attn-impl", default="auto",
                    choices=["auto", "pallas", "reference"],
                    help="attention kernel selection (EngineConfig.attn_impl);"
                         " MLA decode takes the latent Pallas kernel on TPU "
                         "under auto, anywhere under pallas")
    ap.add_argument("--attn-tune-file",
                    default=os.environ.get("LLMD_ATTN_TUNE_FILE"),
                    help="shape-keyed attention block-size table "
                         "(ops/attn_tune JSON, written by bench.py's tuner)")
    ap.add_argument("--moe-dispatch",
                    default=os.environ.get("LLMD_MOE_DISPATCH", "") or "auto",
                    choices=["auto", "sorted", "einsum"],
                    help="MoE token dispatch (EngineConfig.moe_dispatch): "
                         "sorted = token-sorted drop-free path "
                         "(ops/moe_dispatch, all_to_all over ep), einsum = "
                         "legacy capacity dispatch (kill switch; drops past "
                         "capacity); auto = sorted")
    ap.add_argument("--cpu-offload-pages", type=int, default=0,
                    help="KV blocks of CPU offload tier (TPU_OFFLOAD_NUM_CPU_CHUNKS)")
    ap.add_argument("--offload-fs-path", default=None,
                    help="FS tier below the CPU tier (llmd_fs_backend path)")
    ap.add_argument("--spec-mode", default=os.environ.get("LLMD_SPEC_MODE", "off"),
                    choices=["off", "ngram"],
                    help="speculative decoding: 'ngram' = prompt-lookup drafts "
                         "verified through the mixed-batch step (engine/spec.py)")
    ap.add_argument("--spec-tokens", type=int,
                    default=int(os.environ.get("LLMD_SPEC_TOKENS", "4")),
                    help="max draft tokens proposed per sequence per verify step")
    ap.add_argument("--spec-ngram-max", type=int,
                    default=int(os.environ.get("LLMD_SPEC_NGRAM_MAX", "3")),
                    help="longest suffix n-gram the drafter matches")
    ap.add_argument("--spec-ngram-min", type=int,
                    default=int(os.environ.get("LLMD_SPEC_NGRAM_MIN", "1")),
                    help="shortest suffix n-gram the drafter falls back to")
    ap.add_argument("--structured-mode",
                    default=os.environ.get("LLMD_STRUCTURED_MODE", "auto"),
                    choices=["auto", "off"],
                    help="structured outputs (llmd_tpu/structured): 'auto' = "
                         "compile grammars for requests that ask, 'off' = "
                         "reject structured requests as 400")
    ap.add_argument("--decode-chain-depth", type=int,
                    default=int(os.environ.get("LLMD_DECODE_CHAIN_DEPTH", "2")),
                    help="fused decode calls kept in flight per chain "
                         "(EngineConfig.pipeline_depth); deeper chains hide "
                         "more host pack/readback wall behind device compute")
    ap.add_argument("--pack-overlap",
                    default=os.environ.get("LLMD_PACK_OVERLAP", "on"),
                    choices=["on", "off"],
                    help="chained dispatches reuse the in-flight call's "
                         "device-resident tokens/positions/kv-lens and pack "
                         "only changed rows, overlapped with device compute; "
                         "'off' restores the serialized full pack")
    ap.add_argument("--structured-fused",
                    default=os.environ.get("LLMD_STRUCTURED_FUSED", "on"),
                    choices=["on", "off"],
                    help="constrained rows ride the fused masked decode "
                         "program (on-device bias + FSM transition); 'off' "
                         "degrades them to 1-token unified steps")
    ap.add_argument("--structured-table-elems", type=int,
                    default=int(os.environ.get("LLMD_STRUCTURED_TABLE_ELEMS",
                                               str(1 << 23))),
                    help="max staged mask-table size (G_pad*S_pad*V elements) "
                         "before constrained rows degrade to unified steps")
    ap.add_argument("--spec-structured",
                    default=os.environ.get("LLMD_SPEC_STRUCTURED", "on"),
                    choices=["on", "off"],
                    help="constrained rows compose with speculation: drafts "
                         "truncate to their grammar-legal prefix and verify "
                         "through the grammar-masked verify program; 'off' "
                         "restores the legacy never-draft behavior")
    ap.add_argument("--spec-structured-crosscheck",
                    default=os.environ.get("LLMD_SPEC_STRUCTURED_CROSSCHECK",
                                           "off"),
                    choices=["on", "off"],
                    help="debug: re-derive FSM state on host after every "
                         "masked verify step and compare with the device "
                         "state (mismatches adopt the host value)")
    ap.add_argument("--enable-lora", action="store_true",
                    help="enable dynamic LoRA adapter serving")
    ap.add_argument("--max-loras", type=int, default=8)
    ap.add_argument("--max-lora-rank", type=int, default=8)
    ap.add_argument("--cpu", action="store_true", help="force CPU platform (dev)")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent JAX compilation cache dir; the pool "
                         "controller's warm-start path points relaunches at "
                         "the snapshot's cache so compiled programs "
                         "deserialize instead of re-tracing")
    ap.add_argument("--predictor-train-url", default=None,
                    help="latency-predictor training server base URL; completed "
                         "requests' TTFT/TPOT rows stream to its POST /samples")
    ap.add_argument("--data-parallel-size", type=int, default=1, dest="dp",
                    help="wide-EP DP rank engines sharing one SPMD program; each "
                         "rank serves on port+rank (reference --data-parallel-size)")
    ap.add_argument("--expert-parallel-size", type=int, default=1, dest="ep")
    ap.add_argument("--tensor-parallel-size", type=int, default=1, dest="tp")
    ap.add_argument("--sequence-parallel-size", type=int, default=1, dest="sp")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.compile_cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", args.compile_cache_dir)

    from llmd_tpu.engine.config import EngineConfig
    from llmd_tpu.engine.server import EngineServer
    from llmd_tpu.engine.tokenizer import load_tokenizer
    from llmd_tpu.models import resolve_model

    from llmd_tpu.parallel.mesh import MeshConfig

    model_cfg, params = resolve_model(args.model)
    engine_cfg = EngineConfig(
        page_size=args.block_size, num_pages=args.num_pages,
        max_model_len=args.max_model_len, max_batch_size=args.max_batch_size,
        prefill_chunk=args.prefill_chunk, decode_steps=args.decode_steps,
        role=args.role, cpu_offload_pages=args.cpu_offload_pages,
        offload_fs_path=args.offload_fs_path,
        mesh=MeshConfig(dp=args.dp, sp=args.sp, ep=args.ep, tp=args.tp),
        dp_ranks=args.dp,
        quantize_weights=args.quantize,
        kv_cache_dtype=args.kv_cache_dtype,
        kv_layout=args.kv_layout,
        attn_impl=args.attn_impl,
        attn_tune_file=args.attn_tune_file,
        moe_dispatch=args.moe_dispatch,
        spec_mode=args.spec_mode, spec_tokens=args.spec_tokens,
        spec_ngram_max=args.spec_ngram_max, spec_ngram_min=args.spec_ngram_min,
        structured_mode=args.structured_mode,
        pipeline_depth=max(1, args.decode_chain_depth),
        pack_overlap=args.pack_overlap == "on",
        structured_fused_decode=args.structured_fused == "on",
        structured_table_max_elems=args.structured_table_elems,
        spec_structured=args.spec_structured == "on",
        spec_structured_crosscheck=args.spec_structured_crosscheck == "on",
    )
    if args.enable_lora:
        from llmd_tpu.models.lora import LoRAConfig

        engine_cfg.lora = LoRAConfig(max_adapters=args.max_loras,
                                     rank=args.max_lora_rank)
    # an HF checkpoint dir carries its own tokenizer files
    tok_path = args.tokenizer or (args.model if params is not None else None)
    tokenizer = load_tokenizer(tok_path)
    if params is not None and type(tokenizer).__name__ != "HFTokenizer":
        # real weights + byte fallback = garbage completions that look healthy
        raise SystemExit(
            f"could not load an HF tokenizer from {tok_path!r} for real-weight "
            "serving; pass --tokenizer <dir> with tokenizer.json present"
        )
    if args.dp > 1:
        from llmd_tpu.engine.dp_group import WideEPEngineGroup

        group = WideEPEngineGroup(
            model_cfg, engine_cfg,
            model_name=args.served_model_name or f"llmd-tpu/{model_cfg.name}",
            host=args.host, port_base=args.port, tokenizer=tokenizer,
            params=params,
        )

        async def run_group() -> None:
            await group.start()
            print(f"llmd-tpu wide-EP group serving "
                  f"{args.dp} rank engines on {group.endpoints()} "
                  f"(mesh dp={args.dp} sp={args.sp} ep={args.ep} tp={args.tp})",
                  flush=True)
            await asyncio.Event().wait()

        asyncio.run(run_group())
        return
    server = EngineServer(
        model_cfg, engine_cfg,
        model_name=args.served_model_name or f"llmd-tpu/{model_cfg.name}",
        host=args.host, port=args.port, kv_events_port=args.kv_events_port,
        kv_transfer_port=args.kv_transfer_port,
        tokenizer=tokenizer, params=params,
        predictor_train_url=args.predictor_train_url,
    )
    if args.advertise_host:
        server.advertise_host = args.advertise_host

    async def run() -> None:
        await server.start()
        prov = ""
        if args.quantize or args.kv_cache_dtype:
            prov = (f" [weights={args.quantize or 'ckpt-dtype'}, "
                    f"kv={args.kv_cache_dtype or 'ckpt-dtype'}]")
        print(f"llmd-tpu engine serving {server.model_name} on http://{server.address} "
              f"(kv-events port {server.kv_events_port}){prov}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
