"""TPU serving engine: continuous batching over a paged KV cache.

The counterpart of vLLM in the reference stack (docs/architecture/core/model-servers.md)
— but JAX/XLA-native: two jitted programs (chunked prefill, batched decode) with fully
static shapes, a host-side page allocator with content-hash prefix reuse (KV-event
publishing per kv-indexer.md:59-63), and mesh sharding from llmd_tpu.parallel.
"""

from llmd_tpu.engine.config import EngineConfig  # noqa: F401
from llmd_tpu.engine.engine import LLMEngine, EngineOutput  # noqa: F401
