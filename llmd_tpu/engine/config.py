"""Engine configuration (the vLLM flag-surface analogue, TPU-shaped)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from llmd_tpu.parallel.eplb import EPLBConfig
from llmd_tpu.parallel.mesh import MeshConfig


@dataclass
class EngineConfig:
    # Paged KV cache — page_size matches the reference's --block-size contract
    # (precise-prefix-cache-routing values: blockSize must equal engine block size).
    page_size: int = 16
    num_pages: int = 512
    max_model_len: int = 2048
    # Continuous batching
    max_batch_size: int = 8  # decode slots
    prefill_chunk: int = 128  # per-sequence chunked-prefill cap per step
    # Flat token budget of the unified step (--max-num-batched-tokens): decode
    # tokens + prefill chunks from MULTIPLE sequences pack into one program call.
    # None = max(prefill_chunk, max_batch_size) (one chunk + a decode batch).
    max_num_batched_tokens: "int | None" = None
    enable_prefix_caching: bool = True
    # Parallelism
    mesh: MeshConfig = field(default_factory=MeshConfig)
    # DP rank schedulers sharing THIS engine's single SPMD program (wide-EP: each
    # rank is a router-visible endpoint with its own queue/batch-slot-range/page
    # partition, while MoE layers share one all-to-all across mesh.dp × mesh.ep —
    # the reference's --data-parallel-size rank engines, composed the XLA way).
    # Requires max_batch_size and num_pages divisible by dp_ranks; offload tiers
    # are per-rank state and are not yet supported with dp_ranks > 1.
    dp_ranks: int = 1
    # Scheduling
    max_queue: int = 1024
    # Multi-step decode: run N decode iterations in one on-device lax.scan (one host
    # round-trip per N tokens). Stop/max_tokens handled post-hoc by truncation.
    decode_steps: int = 1
    # Pipelined decode dispatch (async output processing): launch call N+1 chained
    # on call N's device-resident sampled tokens, read N's results while N+1 runs —
    # hides the device→host round-trip that otherwise serializes every call.
    pipeline_decode: bool = True
    # In-flight fused-decode calls the host keeps queued (pipeline_decode only).
    # Depth 1 leaves the device idle for one round trip between calls (N+1's
    # launch only reaches the device around the time N's tokens reach the host);
    # depth 2 keeps a launched call behind the running one, so the device goes
    # back-to-back and the host round-trip fully hides. Costs up to
    # depth*decode_steps speculative tokens per sequence at EOS.
    pipeline_depth: int = 2
    # Pipelined prefill sampling: defer the (RTT-priced) host read of a pure-
    # prefill step's sampled first tokens until the next step is on the device.
    # Mixed steps (decode rows present) always apply synchronously — a deferred
    # decode row would sit out the following step. Measured: the read costs a
    # full host<->device round trip (~80 ms tunneled) per prefill step.
    pipeline_prefill_sample: bool = True
    # KV offload tier (pages of CPU-side cache; 0 = disabled) — K3 equivalent
    # (TPU_OFFLOAD_NUM_CPU_CHUNKS / STAGING_BLOCKS knobs of the reference connector).
    cpu_offload_pages: int = 0
    offload_staging_blocks: int = 16
    # Proactive drain: when the plain free list falls below this, demote the oldest
    # LRU pages to the CPU tier in one batched gather (keeps per-page D2H syncs off
    # the allocate() hot path).
    offload_watermark_pages: int = 8
    # FS tier below the CPU tier (llmd_fs_backend shared_storage_path; None = off).
    offload_fs_path: "str | None" = None
    # Out-of-tree KV connector (K5: LMCache/Mooncake/KVBM seam) — a name from
    # llmd_tpu.kv.connector_api's registry; the external engine covers prompt
    # suffixes beyond the local HBM + native CPU/FS tiers.
    kv_connector: "str | None" = None
    kv_connector_params: "dict | None" = None
    # P/D role (disaggregation/README.md roles kv_producer/kv_consumer/both)
    role: str = "both"
    # Attention kernel: "auto" = Pallas ragged-paged-attention on TPU / XLA
    # reference semantics elsewhere, "pallas" = force the Pallas kernel,
    # "reference" = gather+mask (models.transformer.ragged_paged_attention_xla).
    # MLA models: the mixed-batch programs always run the absorbed XLA impl;
    # the fused-decode program takes the latent-width Pallas kernel
    # (ops/mla_decode) on TPU under "auto", anywhere under "pallas".
    attn_impl: str = "auto"
    # Attention block-size auto-tune table (ops/attn_tune): path to the JSON
    # cache bench.py's on-chip tuner exports; pick_block_sizes consults it per
    # (batch, page_size, head layout) before its heuristic. None = resolve
    # LLMD_ATTN_TUNE_FILE from the environment (missing/corrupt files degrade
    # to the heuristic with a warning, never a startup failure).
    attn_tune_file: "str | None" = None
    # Long-context sequence parallelism: when mesh.sp > 1, serve self-contained
    # single-sequence prefill steps through the zig-zag ring-attention program
    # (ops/ring_attention.py) instead of GSPMD-annotated paged attention. The
    # engine gates eligibility per step; decode always stays on the paged path.
    sp_ring_attention: bool = True
    # Per-phase timing attribution (bench.py): forces a device sync after each
    # unified step so host/device/post are separable. Off in production serving —
    # the sync serializes host packing against in-flight device work.
    instrument: bool = False
    # MoE expert GEMMs: "auto" = Pallas grouped GEMM on TPU / einsum elsewhere,
    # "pallas" = force (interpret off-TPU), "einsum" = XLA dot path.
    moe_matmul: str = "auto"
    # MoE token dispatch (ops/moe_dispatch): "sorted" = token-sorted drop-free
    # gather/scatter (all_to_all over the ep axis when ep > 1), "einsum" =
    # legacy dense one-hot capacity dispatch (silently drops tokens past
    # moe_capacity_factor — kept as parity reference and kill switch),
    # "auto" = LLMD_MOE_DISPATCH env override, else sorted everywhere.
    moe_dispatch: str = "auto"
    # Weight-only quantization (models/quant.py): "int8" halves decode's
    # HBM weight traffic — per-output-channel symmetric on the dense
    # projections, the unembedding, and the MoE expert banks (per-expert
    # scales; expert GEMMs then run the scaled-einsum path, and EPLB
    # regathers scales with their slots). None = serve checkpoint dtype.
    quantize_weights: "str | None" = None
    # KV-cache dtype: "fp8" stores pages as float8_e4m3fn — decode's OTHER
    # HBM stream (per-step KV reads rival the weight bytes at serving batch
    # sizes; at b=64/ctx 320 the bf16 KV read is ~1.3 GB/step on llama-1b).
    # The Pallas kernel dequantizes in VMEM after the page DMA (k_scale/
    # v_scale), so HBM traffic halves end to end. None = model dtype.
    kv_cache_dtype: "str | None" = None
    # KV pool lane layout (ops/packed_kv): "packed" stores f = Dhp/head_dim
    # real KV heads per 128-lane row instead of padding each head — for
    # head_dim-64 models that halves KV bytes again (the padding half of
    # every page DMA is zeros). "auto" packs whenever the model is eligible
    # (exact lane fit, Hk divisible); "padded" forces the one-head-per-row
    # layout; "packed" on an ineligible model is an error.
    kv_layout: str = "auto"
    # Expert-parallel load balancing with redundant experts (wide-ep --enable-eplb
    # {window_size, step_interval, num_redundant_experts}); None = disabled.
    eplb: Optional[EPLBConfig] = None
    # LoRA multi-adapter serving (model-servers.md:55-75); None = disabled.
    # Imported lazily to avoid a models<->engine import cycle at module load.
    lora: "object | None" = None  # llmd_tpu.models.lora.LoRAConfig
    # Speculative decoding (engine/spec.py): "off" = plain decode, "ngram" =
    # prompt-lookup drafting verified through the flat mixed-batch program.
    # Greedy acceptance keeps output bitwise identical to spec_mode="off";
    # sequences sampling at temperature > 0 fall back to plain decode.
    spec_mode: str = "off"
    # Max draft tokens proposed (and verified) per sequence per verify step.
    spec_tokens: int = 4
    # Suffix n-gram match lengths tried by the drafter, longest first.
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    # Structured outputs (llmd_tpu/structured): "auto" = compile grammars for
    # requests that ask (guided_* / response_format / logit_bias ride the
    # biased sampler; everything else keeps the exact unbiased programs),
    # "off" = reject structured requests at admission (ValueError -> 400).
    structured_mode: str = "auto"
    # Device-resident decode steady state (PERF.md Lever 12). pack_overlap:
    # while chain N runs on device, the host packs chain N+1 into rotated
    # pre-staged buffers and reuses the in-flight chain's device-resident
    # pos/lens/token outputs, so only the rows that actually changed cross
    # the host->device boundary; the pack wall is accounted as
    # time_pack_overlap (hidden behind device compute) instead of
    # time_host_pack. False restores the legacy serialized pack + accounting.
    pack_overlap: bool = True
    # Constrained rows (grammar masks / logit_bias) ride the fused multi-step
    # decode program with the bias apply + FSM transition done on device
    # (structured/grammar.py dense_tables), instead of degrading the whole
    # batch to 1-token unified steps. Rows combining a grammar AND a
    # logit_bias, or tables past structured_table_max_elems, still degrade.
    structured_fused_decode: bool = True
    # Upper bound on the staged mask-table size (G_pad * S_pad * V elements,
    # f32 bias + i32 next ~= 8 bytes/element). Past this, constrained rows
    # fall back to the unified path rather than staging a huge table.
    structured_table_max_elems: int = 1 << 23
    # Speculation × structured compose (PERF.md Lever 13): constrained rows
    # draft through the host automaton (longest grammar-legal prefix of the
    # n-gram continuation) and verify through the grammar-masked verify
    # program, which returns each row's post-acceptance FSM state so the host
    # resync becomes a recovery path. False restores the legacy behavior:
    # constrained rows never draft and their presence disables verify steps.
    spec_structured: bool = True
    # Debug cross-check: after every masked verify step, re-derive each
    # constrained row's FSM state on host (StructuredState.sync over the
    # accepted tokens) and compare against the device-returned state; a
    # mismatch adopts the host value and bumps
    # stats.spec_fsm_crosscheck_mismatches (should stay 0).
    spec_structured_crosscheck: bool = False

    @property
    def max_pages_per_seq(self) -> int:
        return (self.max_model_len + self.page_size - 1) // self.page_size

    @property
    def batched_tokens(self) -> int:
        if self.max_num_batched_tokens is not None:
            return max(self.max_num_batched_tokens, self.max_batch_size)
        return max(self.prefill_chunk, self.max_batch_size)
