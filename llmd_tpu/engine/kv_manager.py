"""Host-side paged-KV bookkeeping: page allocator with content-hash prefix reuse.

The device cache itself is a JAX array ([L, 2, P, ps, Hk, Dh], models/transformer.py);
this module owns which page holds what:

- free-list allocation,
- automatic prefix caching: completed pages are indexed by chained block hash
  (core/kv_events.hash_block_tokens) and reused by later requests — the engine-side
  feature the reference's prefix-aware routing relies on
  (model-servers.md 'Prefix Cache Reuse'),
- LRU eviction of unreferenced cached pages,
- KV-event emission (BlockStored / BlockRemoved / AllBlocksCleared) for the indexer
  plane (kv-indexer.md:59-63).
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from llmd_tpu.core.kv_events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    KVEvent,
    hash_block_tokens,
)


@dataclass
class PageInfo:
    refs: int = 0
    block_hash: Optional[int] = None  # set once the page holds a complete, hashed block
    lora_id: Optional[str] = None     # adapter the block was computed under


class PageAllocator:
    """Reference-counted page allocator with content-addressed reuse."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        enable_prefix_caching: bool = True,
        event_sink: Optional[Callable[[list[KVEvent]], None]] = None,
        medium: str = "gpu",
        base_id: int = 0,
    ) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        self.enable_prefix_caching = enable_prefix_caching
        self.event_sink = event_sink
        self.medium = medium
        # Called (block_hash, page_id) just before a cached page is recycled —
        # the offload connector's HBM→CPU hook (kv/offload.py).
        self.evict_hook: Optional[Callable[[int, int], None]] = None
        # base_id: first page id owned by this allocator — DP rank engines sharing
        # one device pool each manage a disjoint contiguous id range (wide-EP).
        self.base_id = base_id
        self.free: deque[int] = deque(range(base_id, base_id + num_pages))
        self.pages: dict[int, PageInfo] = {}
        # block_hash → page_id for complete blocks still resident (any refcount)
        self.cached: dict[int, int] = {}
        # refcount-0 cached pages in LRU order (evictable)
        self.lru: OrderedDict[int, int] = OrderedDict()  # block_hash → page_id

    # -- events ------------------------------------------------------------
    def _emit(self, events: list[KVEvent]) -> None:
        if self.event_sink and events:
            self.event_sink(events)

    # -- queries -----------------------------------------------------------
    @property
    def num_free(self) -> int:
        """Pages allocatable right now (truly free + evictable cached)."""
        return len(self.free) + len(self.lru)

    @property
    def num_active(self) -> int:
        return self.num_pages - self.num_free

    def utilization(self) -> float:
        return self.num_active / max(1, self.num_pages)

    def match_prefix(self, block_hashes: list[int]) -> list[int]:
        """Longest consecutive resident prefix → page ids (kv-indexer.md scorer walk)."""
        out: list[int] = []
        for h in block_hashes:
            pid = self.cached.get(h)
            if pid is None:
                break
            out.append(pid)
        return out

    # -- allocation --------------------------------------------------------
    def allocate(self) -> Optional[int]:
        """Allocate a fresh (uncached) page; evict LRU cached page if needed."""
        if self.free:
            pid = self.free.popleft()
        elif self.lru:
            h, pid = self.lru.popitem(last=False)
            if self.evict_hook is not None:
                self.evict_hook(h, pid)
            del self.cached[h]
            del self.pages[pid]
            self._emit([BlockRemoved(block_hashes=[h], medium=self.medium)])
        else:
            return None
        self.pages[pid] = PageInfo(refs=1)
        return pid

    def acquire_cached(self, page_id: int) -> None:
        info = self.pages[page_id]
        if info.refs == 0 and info.block_hash is not None:
            self.lru.pop(info.block_hash, None)
        info.refs += 1

    def commit_block(
        self,
        page_id: int,
        block_hash: int,
        token_ids: list[int],
        parent_hash: Optional[int],
        lora_id: Optional[str] = None,
    ) -> None:
        """Mark a page as holding a complete block; index + announce it."""
        if not self.enable_prefix_caching:
            return
        info = self.pages[page_id]
        if info.block_hash == block_hash:
            return
        if self.cached.get(block_hash) is not None:
            # Same content computed twice (two identical prompts prefilling
            # concurrently). Keep the existing index entry; leave THIS page unhashed so
            # it returns to the plain free list on release — re-indexing would corrupt
            # the cached/lru invariant (one page per hash).
            return
        info.block_hash = block_hash
        info.lora_id = lora_id
        self.cached[block_hash] = page_id
        self._emit([
            BlockStored(
                block_hashes=[block_hash], parent_block_hash=parent_hash,
                token_ids=list(token_ids), block_size=self.page_size,
                lora_id=lora_id, medium=self.medium,
            )
        ])

    def release(self, page_id: int) -> None:
        """Drop one reference; refcount-0 pages stay cached (evictable) or free."""
        info = self.pages.get(page_id)
        if info is None:
            return
        info.refs -= 1
        if info.refs > 0:
            return
        if info.block_hash is not None and self.enable_prefix_caching:
            self.lru[info.block_hash] = page_id
            self.lru.move_to_end(info.block_hash)
        else:
            del self.pages[page_id]
            self.free.append(page_id)

    def demote_lru(self, n: int) -> list[tuple[int, int]]:
        """Pop the n oldest evictable cached pages onto the free list and return
        their (block_hash, page_id) pairs — the offload connector's batched-drain
        entry (one D2H gather for the whole batch instead of per-page syncs in
        allocate()). The evict_hook is NOT called; the caller owns the copy-out,
        which is safe until the freed pages are reallocated AND rewritten."""
        pairs: list[tuple[int, int]] = []
        while self.lru and len(pairs) < n:
            h, pid = self.lru.popitem(last=False)
            pairs.append((h, pid))
            del self.cached[h]
            del self.pages[pid]
            self.free.append(pid)
        if pairs:
            self._emit([BlockRemoved(block_hashes=[h for h, _ in pairs], medium=self.medium)])
        return pairs

    def purge_lora(self, lora_id: str) -> int:
        """Drop cached blocks computed under an adapter (prompt memory reclaim at
        unload). Correctness does not depend on this: block hashes carry the
        generation-scoped lora_key, so stale KV can never match anyway — this
        just frees the pages early. Matches both bare names and "name@gen" keys."""
        removed: list[int] = []
        for h, pid in list(self.cached.items()):
            info = self.pages.get(pid)
            if info is None or info.lora_id is None or not (
                info.lora_id == lora_id or info.lora_id.startswith(lora_id + "@")
            ):
                continue
            del self.cached[h]
            if h in self.lru:  # evictable → page returns to the free list
                self.lru.pop(h)
                del self.pages[pid]
                self.free.append(pid)
            else:  # in use by a live sequence: keeps serving it, never re-matched
                info.block_hash = None
            removed.append(h)
        if removed:
            self._emit([BlockRemoved(block_hashes=removed, medium=self.medium)])
        return len(removed)

    def clear(self) -> None:
        self.free = deque(range(self.base_id, self.base_id + self.num_pages))
        self.pages.clear()
        self.cached.clear()
        self.lru.clear()
        self._emit([AllBlocksCleared()])


@dataclass
class Sequence:
    """One in-flight request's engine-side state."""

    request_id: str
    token_ids: list[int]  # prompt + generated
    prompt_len: int
    max_tokens: int
    sampling: "object" = None  # SamplingParams
    lora_id: Optional[str] = None
    # generation-scoped hash key (engine._lora_hash_key): "name@<load-ns>" when
    # LoRA serving is on, == lora_id otherwise. All block hashing uses THIS, so
    # KV computed under unloaded/reloaded weights can never prefix-match again —
    # in HBM, the CPU tier, or FS files surviving a restart.
    lora_key: Optional[str] = None
    pages: list[int] = field(default_factory=list)
    num_computed: int = 0  # tokens whose KV is resident
    num_cached_prompt: int = 0  # tokens reused from prefix cache
    slot: int = -1  # decode batch slot
    finished: bool = False
    finish_reason: Optional[str] = None
    block_hashes: list[int] = field(default_factory=list)  # chained hashes of committed blocks
    arrival_time: float = 0.0
    first_token_time: Optional[float] = None
    rank: int = 0  # owning DP rank scheduler (wide-EP; 0 in single-rank engines)
    # pod-state features frozen at arrival/admission — the predictor's training
    # rows (latency-predictor.md:58): what the EPP could have observed when it
    # routed this request, joined with the latencies the engine then delivered
    admit_features: Optional[dict] = None
    # multimodal: (content_hash, embeds [mm_tokens, hidden]) per media item, in
    # prompt order; placeholder occurrence j in token_ids draws row j % k of
    # item j // k. Hashes fold into every block key (kv-indexer.md mm extra
    # keys) so two prompts with identical tokens but different media never share
    # cache entries.
    mm_items: list = field(default_factory=list)
    # obs.tracing.SpanContext of the request span (engine.generate) when the
    # request arrived traced — engine step spans parent onto it
    trace_ctx: Optional[object] = None
    # Speculative decoding tallies (engine/spec.py): drafted/accepted feed the
    # per-request acceptance-rate summary observed at retirement.
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Per-sequence draft arming: the prompt-lookup probe is O(context) host
    # work, so a row whose probe came up empty stays disarmed until fresh
    # tokens land for IT (decode/sample/verify). Per-sequence — one
    # non-repetitive stream must not disarm drafting for the whole batch.
    spec_armed: bool = True
    # Arm/disarm transitions over the sequence lifetime — the decision
    # ledger's thrash signal (obs/decisions.py): a high flip count means the
    # probe keeps oscillating between drafting and giving up.
    spec_flips: int = 0
    # Structured outputs (llmd_tpu/structured): the per-sequence automaton
    # cursor (StructuredState) when the request is grammar-constrained. The
    # cursor derives from token_ids, which preemption preserves, so recompute
    # resumes the automaton with no extra state handling.
    structured: Optional[object] = None
    # Static OpenAI logit_bias map (token id -> bias); rides the same device
    # bias-add rows the grammar mask uses.
    logit_bias: Optional[dict] = None

    @property
    def num_generated(self) -> int:
        return len(self.token_ids) - self.prompt_len

    def last_block_hash(self) -> Optional[int]:
        return self.block_hashes[-1] if self.block_hashes else None

    def maybe_commit_blocks(self, alloc: PageAllocator) -> None:
        """Hash+commit any newly completed pages (called after compute advances)."""
        ps = alloc.page_size
        committed = len(self.block_hashes)
        mm = self.mm_hashes()
        while (committed + 1) * ps <= self.num_computed:
            start = committed * ps
            chunk = self.token_ids[start : start + ps]
            key = self.lora_key if self.lora_key is not None else self.lora_id
            h = hash_block_tokens(self.last_block_hash(), chunk, key, mm)
            alloc.commit_block(self.pages[committed], h, chunk, self.last_block_hash(), key)
            self.block_hashes.append(h)
            committed += 1

    def mm_hashes(self) -> list[bytes]:
        return [h for h, _ in self.mm_items]
