"""Step-program registry: compiled-program lifecycle + per-step routing.

The engine's forward work is a small zoo of compiled programs (unified mixed
step, speculative verify, fused decode, their masked/ring variants, the
embedding pool). Before this module, each arrived with ad-hoc wiring: an
``if``-ladder in ``step()`` picked which one ran, attention-impl selection
for the fused-decode shape lived in a private engine method, and the quiesce
invariant tracked exactly one program pair (``n_decode_dispatches ==
n_decode_calls``). Adding a program meant touching all three.

``ProgramRegistry`` makes the set declarative:

* ``register(name, fn, ...)`` stores a compiled (jitted) callable plus its
  routing metadata — an *eligibility predicate* over the engine and a *run*
  hook. jax.jit is lazy, so registering a program costs nothing until its
  first dispatch (``spec_mode=off`` engines never compile the verify
  programs; unconstrained serving never compiles the masked ones).
* ``route(engine)`` returns the first registered program (registration
  order = priority) whose predicate holds — the whole ``step()`` ladder.
  Programs without a ``run`` hook (masked/ring variants, embed) are
  dispatched *by* a routable program, never routed to directly.
* ``record_dispatch``/``record_complete`` count per-program issue/landing;
  ``quiesced()`` generalizes the PR 12 invariant to every program at once —
  asserted at every drain, it catches any dispatch whose result the host
  never read (a leaked in-flight call).
* ``compile_counts()`` exposes each program's jit cache size, the
  recompile-storm probe ``test_paged_attention.py`` pins for fused decode.

``select_decode_attn_impl`` (the fused-decode attention-impl selector,
formerly ``LLMEngine._select_decode_attn_impl``) lives here too: it is
program metadata — which attention kernel the *decode-shaped* programs
compile against — not engine state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


@dataclass
class ProgramSpec:
    """One registry entry: a compiled program plus its routing metadata.

    ``attn`` is provenance only ("mixed" = unified-shape attention impl,
    "decode" = the fused-decode impl from ``select_decode_attn_impl``);
    the actual kernel was bound when the program was traced.
    """

    name: str
    fn: Optional[Callable] = None
    attn: str = "mixed"
    # eligibility predicate over the live engine; None = never routed to
    # directly (the program is dispatched by another program's run hook)
    eligible: Optional[Callable[[Any], bool]] = None
    run: Optional[Callable[[Any], None]] = None


@dataclass
class _Counters:
    dispatched: int = 0
    completed: int = 0


class ProgramRegistry:
    """Ordered program table + per-program dispatch/completion accounting."""

    def __init__(self, on_dispatch: Optional[Callable[[str], None]] = None):
        self._specs: dict[str, ProgramSpec] = {}
        self._counters: dict[str, _Counters] = {}
        self._on_dispatch = on_dispatch

    # ----------------------------------------------------------- registration
    def register(self, name: str, fn: Optional[Callable] = None, *,
                 attn: str = "mixed",
                 eligible: Optional[Callable[[Any], bool]] = None,
                 run: Optional[Callable[[Any], None]] = None) -> Optional[Callable]:
        """Add a program. Returns ``fn`` so the engine can keep its
        ``self._*_fn`` aliases (tests and the hot-path linter key on the
        ``self._*_fn(...)`` call spelling)."""
        if name in self._specs:
            raise ValueError(f"program {name!r} already registered")
        self._specs[name] = ProgramSpec(name=name, fn=fn, attn=attn,
                                        eligible=eligible, run=run)
        self._counters[name] = _Counters()
        return fn

    def fn(self, name: str) -> Optional[Callable]:
        return self._specs[name].fn

    def specs(self) -> list[ProgramSpec]:
        return list(self._specs.values())

    # ---------------------------------------------------------------- routing
    def route(self, engine) -> ProgramSpec:
        """First registered program whose eligibility predicate holds.
        Registration order is the priority order; the last routable program
        must be unconditionally eligible (the engine registers fused decode
        with ``eligible=lambda eng: True``)."""
        for spec in self._specs.values():
            if spec.run is not None and spec.eligible is not None \
                    and spec.eligible(engine):
                return spec
        raise RuntimeError("no eligible step program (registry misconfigured: "
                           "the final routable entry must always be eligible)")

    # ------------------------------------------------------------- accounting
    def record_dispatch(self, name: str) -> None:
        """Count one issued call of ``name``. Unregistered names are allowed
        (pseudo-programs like the deferred prefill sample read) — counters
        auto-create so the quiesce invariant covers them too."""
        c = self._counters.setdefault(name, _Counters())
        c.dispatched += 1
        if self._on_dispatch is not None:
            self._on_dispatch(name)

    def record_complete(self, name: str) -> None:
        c = self._counters.setdefault(name, _Counters())
        c.completed += 1

    def quiesced(self) -> bool:
        """True iff every program's dispatches have been consumed by the host
        — the generalized PR 12 invariant, asserted at every drain."""
        return all(c.dispatched == c.completed for c in self._counters.values())

    def counters(self) -> dict[str, tuple[int, int]]:
        return {n: (c.dispatched, c.completed)
                for n, c in sorted(self._counters.items())}

    def compile_counts(self) -> dict[str, int]:
        """Per-program jit cache sizes (0 for never-traced lazy programs) —
        the recompile-storm probe, now registry-wide."""
        out = {}
        for name, spec in self._specs.items():
            size = getattr(spec.fn, "_cache_size", None)
            if callable(size):
                out[name] = size()
        return out


def select_decode_attn_impl(engine, unified_attn):
    """Attention impl for the FUSED-DECODE-shaped programs only.

    GQA engines share the unified impl (the ragged Pallas kernel already
    serves mixed batches). MLA engines upgrade to the latent-width Pallas
    decode kernel (`ops.mla_decode`): the fused-decode batch is exactly
    its shape — one query row per slot over the single-plane latent pool —
    while unified/verify/embed (mixed chunk shapes) keep the XLA absorbed
    reference. On success ``attn_backend`` becomes
    ``pallas_mla_latent_decode`` and ``attn_fallback_reason`` stays None.

    `attn_impl` semantics on MLA: "auto" takes the kernel on TPU only
    (interpreter-mode Pallas is orders of magnitude slower than the XLA
    reference on CPU meshes); explicit "pallas" forces it anywhere —
    interpret mode off-TPU — and raises on smoke-compile failure, the
    same hard guarantee the explicit mode carries for GQA; "reference"
    keeps the XLA impl everywhere.
    """
    if not engine.model_cfg.is_mla:
        return unified_attn
    mode = engine.cfg.attn_impl
    if mode == "reference":
        return unified_attn
    if mode == "auto" and jax.default_backend() != "tpu":
        return unified_attn
    from llmd_tpu.ops.mla_decode import mla_paged_attention_latent

    try:  # smoke-compile tiny decode shapes so a Mosaic failure can't strand serving
        c = engine.model_cfg
        dhp = engine.cache.shape[-1]  # padded latent width == pool lane width
        ps = engine.cfg.page_size
        q = jnp.zeros((1, c.num_heads, dhp), c.jax_dtype)
        cache = jnp.zeros((2, ps, 1, dhp), engine.kv_dtype)
        mla_paged_attention_latent(
            q, cache, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.ones((1,), jnp.int32),
            scale=(c.mla_qk_nope_dim + c.mla_rope_dim) ** -0.5,
            cu_q_lens=jnp.array([0, 1], jnp.int32),
            num_seqs=jnp.array([1], jnp.int32),
        ).block_until_ready()
        engine.attn_backend = "pallas_mla_latent_decode"
        engine.attn_fallback_reason = None
        return mla_paged_attention_latent
    except Exception as e:  # noqa: BLE001 — any Mosaic/XLA compile error
        if mode == "pallas":
            raise
        engine.attn_fallback_reason = (
            f"mla latent decode smoke-compile failed: {type(e).__name__}: {e}")
        return unified_attn
