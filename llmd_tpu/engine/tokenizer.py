"""Tokenizers: offline-safe byte-level default, HF tokenizer when files exist locally.

The byte tokenizer doubles as the contract shared with the router's token-producer in
tests (testing/fake_server.fake_tokenize uses the same byte mapping for ids 0-255).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """ids 0..255 = raw bytes; 256 = BOS; 257 = EOS."""

    bos_id = 256
    eos_id = 257
    vocab_size = 258

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers tokenizer loaded from a LOCAL path only (zero-egress image)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(path: Optional[str] = None) -> Tokenizer:
    if path and os.path.isdir(path):
        try:
            return HFTokenizer(path)
        except Exception:
            pass
    return ByteTokenizer()
