"""Jitted batched sampler: greedy / temperature / top-k / top-p, static shapes.

One program for the whole decode batch; per-slot parameters arrive as arrays so a mixed
batch (greedy + sampled + different temperatures) is a single XLA launch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def greedy_tokens(logits: jax.Array) -> jax.Array:
    """Greedy token per row, matching `sample_tokens`' temperature<=0 branch
    bitwise: argmax over float32 logits. The speculative verify program
    (engine._make_verify) uses this on every packed position, so accepted
    draft tokens are exactly what sequential greedy decoding would emit.
    """
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def _sample_core(
    logits: jax.Array,  # [B, V] float32
    key: jax.Array,
    temperature: jax.Array,  # [B] (0 = greedy)
    top_k: jax.Array,  # [B] int32 (0 = disabled)
    top_p: jax.Array,  # [B] (1.0 = disabled)
    top_k_max: int,
) -> jax.Array:
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k_max candidates once; per-slot k masking inside.
    topv, topi = jax.lax.top_k(scaled, min(top_k_max, V))  # [B, K]
    K = topv.shape[1]
    ranks = jnp.arange(K)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, K), K)[:, None]
    topv = jnp.where(ranks < k_eff, topv, -jnp.inf)

    # top-p on the (sorted) candidates
    probs = jax.nn.softmax(topv, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]  # keep tokens until mass reached (incl. first)
    topv = jnp.where(keep, topv, -jnp.inf)

    choice = jax.random.categorical(key, topv, axis=-1)  # [B] index into candidates
    sampled = jnp.take_along_axis(topi, choice[:, None], axis=1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled)


@partial(jax.jit, static_argnames=("top_k_max",))
def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    key: jax.Array,
    temperature: jax.Array,  # [B] (0 = greedy)
    top_k: jax.Array,  # [B] int32 (0 = disabled)
    top_p: jax.Array,  # [B] (1.0 = disabled)
    top_k_max: int = 64,
) -> jax.Array:
    """Return sampled token ids [B].

    top-k is bounded by static `top_k_max` (per-slot k masks within the top-k_max
    candidates) to keep shapes static.
    """
    return _sample_core(logits, key, temperature, top_k, top_p, top_k_max)


@partial(jax.jit, static_argnames=("top_k_max",))
def sample_tokens_biased(
    logits: jax.Array,  # [B, V] float32
    bias: jax.Array,  # [B, V] float32 additive (0 allow / -1e9 ban / logit_bias)
    key: jax.Array,
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
    top_k_max: int = 64,
) -> jax.Array:
    """`sample_tokens` with an additive logit bias applied ON DEVICE before
    argmax/sample — the grammar-mask / logit_bias path (llmd_tpu/structured).
    Also inlined (jit-in-jit) by the fused masked decode program
    (engine.py `_decode_multi_masked`), which gathers each row's bias from
    the staged dense tables per scan step — same sampler, bitwise-identical
    tokens whether the bias rides a unified step or a device chain.
    A separate jitted program so engines that never see a structured request
    never compile it (the spec.py lazy-jit pattern): `sample_tokens` keeps its
    exact HLO, and unbiased batches stay bitwise identical."""
    return _sample_core(logits + bias, key, temperature, top_k, top_p,
                        top_k_max)


def apply_penalties(
    logits: jax.Array,  # [B, V]
    output_mask: jax.Array,  # [B, V] bool: token appeared in output
    presence: jax.Array,  # [B]
    frequency_counts: jax.Array,  # [B, V] float
    frequency: jax.Array,  # [B]
    repetition: jax.Array,  # [B] (1.0 = off)
) -> jax.Array:
    logits = logits - presence[:, None] * output_mask
    logits = logits - frequency[:, None] * frequency_counts
    rep = repetition[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    return jnp.where(output_mask, penalized, logits)
