"""Prompt-lookup speculative drafting (Saxena 2023): draft-model-free n-gram
matching against the request's own context.

The drafter is pure host-side numpy over the sequence's token history
(prompt + generated). The last ``n`` tokens are matched against every
earlier position; the tokens that followed the match become the draft.
Longer n-grams are tried first (``spec_ngram_max`` down to
``spec_ngram_min``) because a longer match is a stronger predictor of the
continuation; the first hit wins. Verification happens in the engine's flat
mixed-batch program (engine.py), where greedy acceptance keeps output
bitwise identical to non-speculative decoding — the drafter only has to be
*useful*, never *correct*. Constrained rows (grammar masks / logit_bias)
are drafted the same way; the engine then trims the proposal to its longest
constraint-legal prefix (``LLMEngine._spec_filter_draft``) before the
grammar-masked verify program checks it.

This pays exactly on the traffic the ROADMAP north-star targets: shared
prefixes, agentic tool loops, and summarization, where the output echoes
spans of the prompt or of its own earlier output.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["propose_ngram_draft"]


def propose_ngram_draft(token_ids: Sequence[int], k: int,
                        ngram_max: int = 3, ngram_min: int = 1) -> List[int]:
    """Propose up to ``k`` draft tokens for the next positions of ``token_ids``.

    Matches the suffix n-gram (longest first) anywhere earlier in the
    sequence and proposes the continuation that followed it. Returns [] when
    nothing matches — the engine then falls back to plain decode for this
    sequence, so an empty draft is always safe.
    """
    L = len(token_ids)
    if k <= 0 or L < ngram_min + 1:
        return []
    # llmd-lint: allow[hot-host-sync] token_ids is a host-side int list; no device transfer happens here
    arr = np.asarray(token_ids, dtype=np.int64)
    # n may not exceed L-1: the suffix itself must leave at least one earlier
    # position to match against.
    for n in range(min(ngram_max, L - 1), max(ngram_min, 1) - 1, -1):
        pattern = arr[L - n:]
        # Candidate window starts: exclude the suffix occurrence itself.
        windows = np.lib.stride_tricks.sliding_window_view(arr, n)[:L - n]
        hits = np.nonzero((windows == pattern).all(axis=1))[0]
        if hits.size == 0:
            continue
        # Most recent occurrence that still has a full k-token continuation
        # (recent context is the better predictor for cyclic/echo traffic) —
        # a match butting against the end of the sequence would truncate the
        # draft to almost nothing. Fall back to the earliest hit, whose
        # continuation window is the longest available.
        full = hits[hits <= L - n - k]
        i = int(full[-1]) if full.size else int(hits[0])
        draft = arr[i + n:i + n + k]
        if draft.size:
            return [int(t) for t in draft]
    return []
