"""Data-parallel rank groups — the wide-EP orchestration layer.

TPU-native equivalent of vLLM's DP launcher flags the reference drives through LWS
(`guides/wide-ep-lws/modelserver/gpu/vllm/base/decode.yaml:85-108`):
``--data-parallel-size`` (total ranks) / ``--data-parallel-size-local`` (ranks on
this host) / ``--data-parallel-address`` + ``--data-parallel-rpc-port`` (leader
coordination endpoint) / ``--data-parallel-start-rank`` (from LWS_WORKER_INDEX) /
``--data-parallel-hybrid-lb``.

Pieces:
- ``DPCoordinator`` — the leader's rpc endpoint (JSON-lines over TCP). Ranks
  register at startup (barrier) and report ``has_work`` every loop tick; the
  coordinator answers with the *wave* decision: if ANY rank has work, ALL ranks
  step. MoE expert-parallel all-to-all is a collective — in a real multi-host SPMD
  program every rank must enter the step together or the fabric deadlocks; idle
  ranks contribute empty batches (vLLM's DP wave semantics).
- ``DPWorkerSync`` — blocking-socket client used from the engine step-loop thread.
- ``DPAsyncEngine`` — AsyncLLMEngine whose loop steps on wave decisions.
- ``DPEngineGroup`` — dp_size_local engine servers on consecutive ports
  (``port_base + i`` — the reference's rank ports 8000-8007, which the router lists
  as one endpoint per ``podIP:port``, InferencePool targetPorts ≤ 8), plus an
  optional node-local round-robin balancer for hybrid-LB mode (external LB sees one
  endpoint per node, the node spreads internally).
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
from dataclasses import dataclass, field
from typing import Optional

from llmd_tpu.engine.async_engine import AsyncLLMEngine
from llmd_tpu.engine.config import EngineConfig
from llmd_tpu.engine.engine import LLMEngine
from llmd_tpu.engine.server import EngineServer
from llmd_tpu.models.config import ModelConfig

MAX_TARGET_PORTS = 8  # InferencePool targetPorts limit (docs/api-reference/inferencepool.md)


@dataclass
class DPGroupConfig:
    dp_size: int = 1          # total ranks across all hosts
    dp_size_local: int = 1    # ranks served by this process/host
    dp_address: str = "127.0.0.1"  # leader coordination host
    dp_rpc_port: int = 5555   # leader coordination port (0 = ephemeral)
    dp_start_rank: int = 0    # first global rank on this host
    hybrid_lb: bool = False   # expose one balanced endpoint per node
    port_base: int = 8000     # local rank i serves on port_base + i (0 = ephemeral)
    lb_port: int = 0          # hybrid-LB listen port (0 = ephemeral)

    def __post_init__(self) -> None:
        if self.dp_size_local > self.dp_size:
            raise ValueError("dp_size_local > dp_size")
        if not self.hybrid_lb and self.dp_size_local > MAX_TARGET_PORTS:
            raise ValueError(
                f"{self.dp_size_local} rank ports exceed InferencePool's "
                f"{MAX_TARGET_PORTS}-port limit; use hybrid_lb"
            )

    @property
    def is_leader(self) -> bool:
        return self.dp_start_rank == 0


class DPCoordinator:
    """Leader-side rank registry + wave clock (JSON-lines TCP server)."""

    def __init__(self, dp_size: int, host: str = "0.0.0.0", port: int = 0) -> None:
        self.dp_size = dp_size
        self.host, self.port = host, port
        self.registered: set[int] = set()
        self.has_work: dict[int, bool] = {}
        self.waves = 0  # wave ticks answered with step=True
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Force-close live worker connections first: wait_closed() (Python
            # 3.12+) waits for every handler to finish, and a handler sitting in
            # readline() on an open conn would wedge group shutdown.
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    writer.write(b'{"error": "bad json"}\n')
                    await writer.drain()
                    continue
                writer.write((json.dumps(self._dispatch(msg)) + "\n").encode())
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except RuntimeError:
                pass  # loop already closed

    def _dispatch(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "register":
            rank = int(msg["rank"])
            self.registered.add(rank)
            self.has_work.setdefault(rank, False)
            return {"ok": True, "dp_size": self.dp_size,
                    "registered": len(self.registered)}
        if cmd == "report":
            self.has_work[int(msg["rank"])] = bool(msg.get("has_work"))
            step = any(self.has_work.values())
            if step:
                self.waves += 1
            return {"step": step}
        if cmd == "status":
            return {"registered": sorted(self.registered),
                    "dp_size": self.dp_size,
                    "wave": any(self.has_work.values()), "waves": self.waves}
        return {"error": f"unknown cmd {cmd!r}"}


class DPWorkerSync:
    """Blocking JSON-lines client for the engine loop thread (one conn per rank)."""

    def __init__(self, rank: int, host: str, port: int, timeout_s: float = 5.0) -> None:
        self.rank = rank
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._file = None

    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
        self._file = self._sock.makefile("rwb")

    def _rpc(self, msg: dict) -> dict:
        if self._sock is None:
            self._connect()
        self._file.write((json.dumps(msg) + "\n").encode())
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("coordinator closed connection")
        return json.loads(line)

    def register(self, barrier_timeout_s: float = 30.0) -> None:
        """Register and block until every rank in the group has registered."""
        deadline = time.monotonic() + barrier_timeout_s
        resp = self._rpc({"cmd": "register", "rank": self.rank})
        dp_size = resp["dp_size"]
        while resp.get("registered", 0) < dp_size:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {self.rank}: {resp.get('registered')}/{dp_size} ranks "
                    f"registered after {barrier_timeout_s}s"
                )
            time.sleep(0.05)
            resp = self._rpc({"cmd": "register", "rank": self.rank})

    def report(self, has_work: bool) -> bool:
        """Raises OSError/ConnectionError/JSONDecodeError on coordinator outage —
        the caller must drop to solo mode and re-register on its paced schedule.
        (Swallowing here made DPAsyncEngine re-attempt the blocking connect every
        step: up to timeout_s of stall per step after an outage, contradicting the
        solo-serving degradation contract.)"""
        try:
            resp = self._rpc({"cmd": "report", "rank": self.rank,
                              "has_work": has_work})
        except (OSError, json.JSONDecodeError):
            self.close()
            raise
        if "step" not in resp:
            # error response (corrupted line, version skew) — same contract as a
            # transport outage: caller deregisters and serves solo
            self.close()
            raise ConnectionError(f"coordinator error response: {resp!r}")
        return bool(resp["step"])

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class DPAsyncEngine(AsyncLLMEngine):
    """Engine loop that enters steps on the group wave, not local work alone.

    Degradation contract: if the coordination plane is unreachable (peer rank
    crashed at startup, wrong dp_address), the rank serves *solo* — stepping on
    local work only — and keeps retrying registration between steps. The loop
    thread must never die while the HTTP server accepts requests, or they would
    hang unanswered forever.
    """

    def __init__(self, engine: LLMEngine, worker: DPWorkerSync,
                 idle_sleep_s: float = 0.002,
                 register_attempt_timeout_s: float = 2.0,
                 register_retry_interval_s: float = 5.0) -> None:
        super().__init__(engine, idle_sleep_s=idle_sleep_s)
        self.worker = worker
        self.steps = 0
        self.empty_steps = 0  # wave-joined steps with no local work
        self.register_attempt_timeout_s = register_attempt_timeout_s
        self.register_retry_interval_s = register_retry_interval_s
        self.register_failures = 0
        self.registered = False
        self._next_register = 0.0

    def _try_register(self) -> None:
        # paced: a blocked register attempt (dead leader, slow peer) costs up to
        # attempt_timeout once per retry interval — solo serving keeps full rate
        # in between instead of stalling seconds per step
        now = time.monotonic()
        if now < self._next_register:
            return
        try:
            self.worker.register(barrier_timeout_s=self.register_attempt_timeout_s)
            self.registered = True
        except Exception:
            self.register_failures += 1
            self.worker.close()
            self._next_register = time.monotonic() + self.register_retry_interval_s

    def _run(self) -> None:  # overrides the base loop
        while not self._stop.is_set():
            if not self.registered:
                self._try_register()
            with self._lock:
                has_work = self.engine.has_work()
            if self.registered:
                try:
                    step = self.worker.report(has_work)
                except (OSError, ConnectionError, json.JSONDecodeError):
                    # coordinator outage: serve solo at full rate and re-register
                    # on the paced schedule (don't pay a connect timeout per step)
                    self.registered = False
                    self.register_failures += 1
                    self._next_register = time.monotonic() + self.register_retry_interval_s
                    step = has_work
            else:
                step = has_work
            if not step:
                time.sleep(self._idle_sleep)
                continue
            with self._lock:
                outputs = self.engine.step()
            self.steps += 1
            if not has_work:
                # joined the wave with an empty batch: locally that's a no-op, so
                # pace the loop (on real multi-host SPMD the collective itself
                # would block here)
                self.empty_steps += 1
                time.sleep(self._idle_sleep)
            for out in outputs:
                with self._lock:
                    entry = self._streams.get(out.request_id)
                    if out.finished:
                        self._streams.pop(out.request_id, None)
                if entry is None:
                    continue
                loop, q = entry
                loop.call_soon_threadsafe(q.put_nowait, out)
        self.worker.close()


class DPLocalBalancer:
    """Node-local round-robin reverse proxy for hybrid-LB mode."""

    def __init__(self, targets: list[str], host: str = "127.0.0.1", port: int = 0) -> None:
        self.targets = targets
        self.host, self.port = host, port
        self._i = 0
        self._runner = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        import aiohttp
        from aiohttp import web

        self._session = aiohttp.ClientSession()

        async def proxy(request: web.Request):
            target = self.targets[self._i % len(self.targets)]
            self._i += 1
            body = await request.read()
            async with self._session.request(
                request.method, f"http://{target}{request.path_qs}",
                data=body or None,
                headers={k: v for k, v in request.headers.items()
                         if k.lower() not in ("host", "content-length")},
            ) as resp:
                out = web.StreamResponse(status=resp.status, headers={
                    k: v for k, v in resp.headers.items()
                    if k.lower() not in ("content-length", "transfer-encoding")})
                await out.prepare(request)
                async for chunk in resp.content.iter_any():
                    await out.write(chunk)
                await out.write_eof()
                return out

        app = web.Application(client_max_size=32 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", proxy)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            await self._session.close()


class WideEPEngineGroup:
    """DP rank engines sharing ONE SPMD program over a (dp, sp, ep, tp) mesh —
    the wide-EP topology of the reference (`wide-ep-lws decode.yaml:85-121`),
    composed the XLA way.

    The reference runs R vLLM rank engines whose MoE layers meet in a DeepEP
    all-to-all; here the R ranks are scheduler frontends over one jitted step:
    each rank owns a router-visible HTTP port (InferencePool targetPorts — one
    endpoint per ``podIP:port``), its own request queue, batch-slot range and KV
    page partition, while the step program's token axis is sharded over ``dp``
    and the MoE expert dim over ``ep`` — GSPMD lowers the dispatch/combine
    einsums to one all-to-all spanning dp×ep, i.e. ALL ranks' devices, exactly
    the shared fabric collective of the reference topology. Wave lockstep is
    inherent: one step program serves every rank, so an idle rank simply
    contributes no rows (vLLM's DP wave semantics without an RPC plane; the
    cross-host RPC version remains `DPCoordinator`/`DPEngineGroup`).

    Current dryrun simplification (documented, not hidden): the KV page pool is
    replicated over dp — a production layout shards it by reordering the pool
    page-major so each rank's partition is a contiguous device-local block.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        ranks: Optional[int] = None,
        model_name: str = "llmd-tpu/model",
        host: str = "127.0.0.1",
        port_base: int = 0,
        tokenizer=None,
        params=None,
    ) -> None:
        from llmd_tpu.engine.async_engine import AsyncLLMEngine

        self.ranks = ranks if ranks is not None else max(1, engine_cfg.mesh.dp)
        if engine_cfg.dp_ranks == 1 and self.ranks > 1:
            from dataclasses import replace as _replace

            engine_cfg = _replace(engine_cfg, dp_ranks=self.ranks)
        if engine_cfg.dp_ranks != self.ranks:
            raise ValueError(f"dp_ranks={engine_cfg.dp_ranks} != ranks={self.ranks}")
        if self.ranks > MAX_TARGET_PORTS:
            raise ValueError(
                f"{self.ranks} rank ports exceed InferencePool's "
                f"{MAX_TARGET_PORTS}-port limit")
        self.engine = LLMEngine(model_cfg, engine_cfg, params=params)
        self.async_engine = AsyncLLMEngine(self.engine)
        self.servers: list[EngineServer] = []
        for r in range(self.ranks):
            srv = EngineServer(
                model_cfg, engine_cfg, model_name=model_name, host=host,
                port=port_base + r if port_base else 0, tokenizer=tokenizer,
                engine=self.engine, async_engine=self.async_engine, rank=r,
            )
            self.servers.append(srv)

    async def start(self) -> None:
        for srv in self.servers:
            await srv.start()

    async def stop(self) -> None:
        self.async_engine.stop()
        for srv in self.servers:
            await srv.stop()

    def endpoints(self) -> list[str]:
        """One router-visible address per DP rank (EPP routes to every rank port)."""
        return [s.address for s in self.servers]


class DPEngineGroup:
    """dp_size_local engine servers + coordinator (on the leader) + optional LB."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        dp_cfg: DPGroupConfig,
        model_name: str = "llmd-tpu/model",
        host: str = "127.0.0.1",
        tokenizer=None,
        params=None,
    ) -> None:
        self.dp_cfg = dp_cfg
        self.coordinator = (
            DPCoordinator(dp_cfg.dp_size, port=dp_cfg.dp_rpc_port)
            if dp_cfg.is_leader else None
        )
        self.servers: list[EngineServer] = []
        self.balancer: Optional[DPLocalBalancer] = None
        self._model_cfg, self._engine_cfg = model_cfg, engine_cfg
        self._model_name, self._host = model_name, host
        self._tokenizer, self._params = tokenizer, params

    async def start(self) -> None:
        if self.coordinator is not None:
            await self.coordinator.start()
        rpc_host, rpc_port = self.dp_cfg.dp_address, (
            self.coordinator.port if self.coordinator is not None
            else self.dp_cfg.dp_rpc_port
        )
        for i in range(self.dp_cfg.dp_size_local):
            rank = self.dp_cfg.dp_start_rank + i
            port = self.dp_cfg.port_base + i if self.dp_cfg.port_base else 0
            srv = EngineServer(
                self._model_cfg, self._engine_cfg, model_name=self._model_name,
                host=self._host, port=port, tokenizer=self._tokenizer,
                params=self._params,
            )
            # swap in the wave-synced loop before start() spawns the thread
            srv.async_engine = DPAsyncEngine(
                srv.engine, DPWorkerSync(rank, rpc_host, rpc_port))
            self.servers.append(srv)
            await srv.start()
        if self.dp_cfg.hybrid_lb:
            self.balancer = DPLocalBalancer(
                [s.address for s in self.servers], host=self._host,
                port=self.dp_cfg.lb_port)
            await self.balancer.start()

    async def stop(self) -> None:
        for srv in self.servers:
            await srv.stop()
        if self.balancer is not None:
            await self.balancer.stop()
        if self.coordinator is not None:
            await self.coordinator.stop()

    def endpoints(self) -> list[str]:
        """Addresses the router should list: one per rank port (default — the EPP
        'route to all DP rank ports' contract), or the node balancer (hybrid-LB)."""
        if self.dp_cfg.hybrid_lb:
            assert self.balancer is not None, "group not started"
            return [self.balancer.address]
        return [s.address for s in self.servers]
