"""OpenAI-compatible HTTP server over the TPU engine.

Implements the model-server contract the router consumes (reference
docs/architecture/core/model-servers.md): OpenAI endpoints (+SSE streaming), render
endpoints for the router's token-producer (kv-indexer.md:104-113), Prometheus /metrics
with vLLM-compatible names (:38-52), /health probes (:81-86), and ZMQ KV-event
publishing in pod-discovery mode (kv-indexer.md:67-87).

P/D disaggregation (disaggregation/README.md): with ``kv_transfer_port`` set, the
server exposes the KV-transfer side channel — requests carrying
``kv_transfer_params.do_remote_decode`` export their prefill KV for remote pull;
requests carrying ``do_remote_prefill`` pull + inject remote KV before compute
(falling back to recompute on any failure).

Run: python -m llmd_tpu.engine.serve --model tiny --port 8000
"""

from __future__ import annotations

import asyncio
import json
import sys
import time
import uuid
from typing import Optional

from aiohttp import web

from llmd_tpu.core.kv_events import KVEvent, encode_event_batch, kv_topic
from llmd_tpu.core.request import (
    HDR_REQUEST_TIMEOUT,
    SamplingParams,
    flatten_messages,
)
from llmd_tpu.disagg.transfer import (
    KVTransferParams,
    export_begin,
    export_finish,
    inject_into_engine,
)
from llmd_tpu.engine.async_engine import AsyncLLMEngine
from llmd_tpu.engine.config import EngineConfig
from llmd_tpu.engine.engine import LLMEngine
from llmd_tpu.engine.tokenizer import Tokenizer, load_tokenizer
from llmd_tpu.models.config import ModelConfig
from llmd_tpu.structured import validate_structured_body


def _body_has_media(body: dict) -> bool:
    from llmd_tpu.disagg.encode import iter_media_parts

    return bool(body.get("mm_items")) or next(iter_media_parts(body), None) is not None


def _sampling_from_body(body: dict) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(body.get("max_tokens", 16)),
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),
        stop=body.get("stop") or (),
        seed=body.get("seed"),
        n=int(body.get("n", 1)),
        presence_penalty=float(body.get("presence_penalty", 0.0)),
        frequency_penalty=float(body.get("frequency_penalty", 0.0)),
        ignore_eos=bool(body.get("ignore_eos", False)),
        guided_choice=body.get("guided_choice"),
        guided_regex=body.get("guided_regex"),
        response_format=body.get("response_format"),
        logit_bias=body.get("logit_bias"),
    )


class EngineServer:
    def __init__(
        self,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        model_name: str = "llmd-tpu/model",
        host: str = "127.0.0.1",
        port: int = 8000,
        kv_events_port: Optional[int] = None,
        kv_transfer_port: Optional[int] = None,
        tokenizer: Optional[Tokenizer] = None,
        params=None,
        engine: Optional[LLMEngine] = None,
        async_engine: Optional["AsyncLLMEngine"] = None,
        rank: int = 0,
        predictor_train_url: Optional[str] = None,
    ) -> None:
        self.model_name = model_name
        self.host, self.port = host, port
        self.tokenizer = tokenizer or load_tokenizer()
        self.kv_events_port = kv_events_port
        self.kv_transfer_port = kv_transfer_port
        self.advertise_host: Optional[str] = None  # routable host for transfer handles
        self.transfer_source = None
        self.transfer_client = None
        self.transfer_stats = {"injected_blocks": 0, "pull_failures": 0,
                               "prefix_pulls": 0, "prefix_pull_blocks": 0,
                               "released": 0}
        # KV-plane pulls whose peer-side registration may still be live:
        # local rid → (host, port, remote_request_id). Released on request
        # retire/abort so a dead puller never pins peer exports until TTL.
        self._pending_pulls: dict[str, tuple] = {}
        self._zctx = None
        self._pub = None
        self._kv_seq = 0
        # training-sidecar feed: completed requests' latency rows stream to the
        # predictor's POST /samples (the reference's vllm→trainer scrape flow)
        self.predictor_train_url = predictor_train_url
        self._pending_events: list[KVEvent] = []
        self._ev_lock = __import__("threading").Lock()

        # Wide-EP rank frontends share one engine + step loop; each server is a
        # router-visible endpoint feeding its own rank queue (decode.yaml rank
        # ports semantics). Standalone servers build their own engine.
        self.rank = rank
        if engine is not None:
            if async_engine is None:
                # two private step loops over one engine would race the scheduler
                raise ValueError("a shared engine requires the shared async_engine")
            self.engine = engine
            self.async_engine = async_engine
            if engine.tokenizer is None:
                # shared engines built without one still serve structured
                # requests through this frontend's tokenizer
                engine.tokenizer = self.tokenizer
            # this frontend's rank publishes its own KV events
            if rank < len(engine.allocs):
                engine.allocs[rank].event_sink = self._on_kv_events
        else:
            self.engine = LLMEngine(model_cfg, engine_cfg, params=params,
                                    event_sink=self._on_kv_events,
                                    tokenizer=self.tokenizer)
            self.async_engine = AsyncLLMEngine(self.engine)
        self._runner: Optional[web.AppRunner] = None
        self.request_count = 0
        # Device-plane monitor (obs/device.py): created at start() by the
        # server that owns the engine; wide-EP rank frontends share the
        # engine's instance and only the creator stops it.
        self.monitor = None
        self._owns_monitor = False
        # graceful drain (POST /drain): admissions stop, in-flight requests
        # finish, /health reports draining so the router routes around us
        self._draining = False
        self._vision = None  # lazy in-process vision tower (combined-PD mode)
        self._vision_lock = __import__("threading").Lock()  # one compile, ever
        # Conversations API store (pod-local; router keeps traffic sticky by
        # id). LRU-capped: abandoned conversations must not grow without bound.
        from collections import OrderedDict

        self._conversations: "OrderedDict[str, dict]" = OrderedDict()
        self._max_conversations = 4096
        # per-conversation growth is ALSO capped: one long-lived conversation
        # appending forever must not grow pod memory unboundedly — past the
        # cap the oldest items roll off (context-window semantics)
        self._max_conv_items = 512
        from llmd_tpu.obs.tracing import global_tracer

        self.tracer = global_tracer()  # engine hop joins the EPP trace
        # Frontend-owned metric families live in a per-server registry (each
        # wide-EP rank frontend counts its own requests/transfers); engine-
        # loop families live in engine.registry. /metrics renders both.
        from llmd_tpu.obs.metrics import Registry, register_engine_server_metrics

        self.registry = Registry()
        self.server_metrics = register_engine_server_metrics(self.registry)
        self.server_metrics.requests.set_function(lambda: self.request_count)
        for key in ("injected_blocks", "pull_failures", "prefix_pulls",
                    "prefix_pull_blocks", "released"):
            self.server_metrics.transfer[key].set_function(
                lambda k=key: self.transfer_stats[k])
        for key in ("exports", "pulls", "notifies", "expired"):
            self.server_metrics.transfer[key].set_function(
                lambda k=key: self.transfer_source.stats.get(k, 0)
                if self.transfer_source is not None else 0)
        self.server_metrics.transfer_registrations.set_function(
            lambda: len(self.transfer_source)
            if self.transfer_source is not None else 0)
        # durable prefix tier (kv/writeback.py): flush-queue depth + breaker
        # gauges read live state; the flush counter and kv_flush flight event
        # are driven by the queue's on_flush callback (worker thread)
        self.server_metrics.kv_durable_queue_depth.set_function(
            lambda: self.engine.writeback.depth()
            if getattr(self.engine, "writeback", None) is not None else 0)
        self.server_metrics.kv_durable_breaker.set_function(
            lambda: self.engine.durable.breaker_state()
            if getattr(self.engine, "durable", None) is not None else 0.0)
        wb = getattr(self.engine, "writeback", None)
        if wb is not None and wb.on_flush is None:

            def _on_flush(outcome: str, n_blocks: int) -> None:
                self.server_metrics.kv_durable_flush.labels(
                    outcome=outcome).inc(n_blocks)
                self.engine.flight.record_system(
                    "kv_flush", outcome=outcome, n_blocks=n_blocks)

            wb.on_flush = _on_flush

    # -- KV events ---------------------------------------------------------
    def _on_kv_events(self, events: list[KVEvent]) -> None:
        """Called from the engine thread; buffered, flushed on the event loop."""
        if self.kv_events_port is None:
            return
        with self._ev_lock:
            self._pending_events.extend(events)

    async def _kv_flush_loop(self) -> None:
        import zmq

        while True:
            await asyncio.sleep(0.01)
            with self._ev_lock:
                events, self._pending_events = self._pending_events, []
            if events and self._pub is not None:
                self._kv_seq += 1
                topic = kv_topic(self.address, self.model_name).encode()
                try:
                    await self._pub.send_multipart(
                        [topic, encode_event_batch(events, self._kv_seq)], flags=zmq.NOBLOCK
                    )
                except Exception:
                    pass  # PUB with no subscribers / full HWM: drop (fire-and-forget)

    async def _trace_flush_loop(self) -> None:
        """Forward engine-emitted latency rows to the predictor trainer."""
        import aiohttp

        while True:
            await asyncio.sleep(1.0)
            rows = self.engine.drain_latency_trace()
            if not rows:
                continue
            try:
                async with aiohttp.ClientSession() as sess:
                    await sess.post(f"{self.predictor_train_url}/samples",
                                    json={"samples": rows},
                                    timeout=aiohttp.ClientTimeout(total=2.0))
            except Exception:
                pass  # trainer down: rows already drained, next batch retries fresh

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self.async_engine.start()
        from llmd_tpu.obs.device import DeviceMonitor

        mon = getattr(self.engine, "monitor", None)
        if mon is None:
            # pending_fn reads engine.seqs truthiness lock-free (GIL-atomic):
            # the watchdog must never wait on the engine lock — a hung step()
            # holds it, and that hang is exactly what it detects
            mon = DeviceMonitor(
                self.engine.registry, flight=self.engine.flight,
                pending_fn=lambda: bool(self.engine.seqs))
            self.engine.monitor = mon
            mon.start()
            self._owns_monitor = True
        self.monitor = mon
        if self.kv_transfer_port is not None:
            from llmd_tpu.disagg.transfer import KVTransferClient, KVTransferSource

            self.transfer_source = KVTransferSource(port=self.kv_transfer_port)
            from llmd_tpu.kvplane import plane_mode, serve_prefix

            if plane_mode() == "precise":
                # KV plane: serve peers' pull_prefix requests from the local
                # prefix cache (set before start(): selects the python
                # transport, which speaks the op; LLMD_KV_PLANE=off keeps the
                # transfer source byte-identical to the pre-plane behavior)
                self.transfer_source.prefix_provider = (
                    lambda hashes, rid: serve_prefix(self, hashes, rid))
            self.transfer_source.start()
            self.kv_transfer_port = self.transfer_source.port
            self.transfer_client = KVTransferClient()
        app = web.Application(client_max_size=32 * 1024 * 1024)
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions/render", self._render)
        app.router.add_post("/v1/chat/completions/render", self._render)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        app.router.add_post("/drain", self._drain)
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/load_lora_adapter", self._load_lora)
        app.router.add_post("/v1/unload_lora_adapter", self._unload_lora)
        app.router.add_post("/v1/embeddings", self._embeddings)
        # OpenAI Responses + Conversations APIs (epp-http-apis.md:11,153-183;
        # request-handling.md:73 lists both under the openai parser)
        app.router.add_post("/v1/responses", self._responses)
        app.router.add_post("/v1/conversations", self._conv_create)
        app.router.add_get("/v1/conversations/{cid}", self._conv_get)
        app.router.add_delete("/v1/conversations/{cid}", self._conv_delete)
        app.router.add_post("/v1/conversations/{cid}/items", self._conv_add_items)
        app.router.add_get("/v1/conversations/{cid}/items", self._conv_list_items)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/requests/{rid}", self._debug_request)
        app.router.add_get("/debug/profile", self._debug_profile)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        if self.kv_events_port is not None:
            import zmq
            import zmq.asyncio

            self._zctx = zmq.asyncio.Context()
            self._pub = self._zctx.socket(zmq.PUB)
            if self.kv_events_port == 0:
                self.kv_events_port = self._pub.bind_to_random_port("tcp://0.0.0.0")
            else:
                self._pub.bind(f"tcp://0.0.0.0:{self.kv_events_port}")
            asyncio.get_running_loop().create_task(self._kv_flush_loop())
        if self.predictor_train_url is not None:
            asyncio.get_running_loop().create_task(self._trace_flush_loop())

    async def stop(self) -> None:
        if self._owns_monitor and self.monitor is not None:
            self.monitor.stop()
            self.engine.monitor = None
        self.async_engine.stop()
        if getattr(self.engine, "writeback", None) is not None:
            self.engine.writeback.stop()
        if self.transfer_source is not None:
            self.transfer_source.stop()
        if self._runner:
            await self._runner.cleanup()
        if self._pub is not None:
            self._pub.close(0)
            self._zctx.term()

    # -- helpers -----------------------------------------------------------
    def _pull_remote_kv(self, ktp: "KVTransferParams", token_ids: list[int],
                        lora_id=None, mm_hashes: list = (),
                        rid: Optional[str] = None) -> int:
        """Pull + inject remote prefill KV; any failure → recompute locally
        (kv_load_failure_policy=recompute, operations-vllm.md:84-100)."""
        if rid is not None:
            self._pending_pulls[rid] = (ktp.remote_host, ktp.remote_port,
                                        ktp.remote_request_id)
        try:
            pulled = self.transfer_client.pull(
                ktp.remote_host, ktp.remote_port, ktp.remote_request_id
            )
            if pulled is None:
                self.transfer_stats["pull_failures"] += 1
                return 0
            n = self.async_engine.run_locked(
                lambda: inject_into_engine(self.engine, pulled, token_ids, lora_id,
                                           mm_hashes)
            )
            self.transfer_stats["injected_blocks"] += n
            # free producer-side blocks (NIXL-notify semantics)
            if self.transfer_client.notify(ktp.remote_host, ktp.remote_port,
                                           ktp.remote_request_id) and rid is not None:
                self._pending_pulls.pop(rid, None)
            return n
        except Exception as e:
            self.transfer_stats["pull_failures"] += 1
            if isinstance(e, ValueError) and "block shape" in str(e):
                # peer layout/geometry mismatch is a standing config error —
                # every pull will fail until fixed; say so once per minute
                # instead of burying it in the failure counter
                now = time.monotonic()
                if now - getattr(self, "_shape_err_ts", 0.0) > 60.0:
                    self._shape_err_ts = now
                    print(f"kv-transfer: {e}", file=sys.stderr, flush=True)
            return 0

    def _pull_prefix_kv(self, rid: str, ktp: "KVTransferParams",
                        token_ids: list[int], lora_id=None,
                        mm_hashes: list = ()) -> int:
        """KV-plane prefix pull ahead of prefill: the peer rung first (when
        the router stamped one), then the cluster-durable store. Any failure
        degrades to the normal admission ladder (host/disk offload tier, then
        re-prefill) — it NEVER fails the request. Injected blocks become
        ordinary local prefix hits, so num_cached_prompt stays truthful."""
        from llmd_tpu.kvplane import pull_prefix_into

        self.transfer_stats["prefix_pulls"] += 1
        t0 = time.monotonic()
        tier = getattr(ktp, "tier", "peer") or "peer"
        peer = f"{ktp.remote_host}:{ktp.remote_port}"
        n, outcome = 0, "miss"
        if (tier == "peer" and ktp.remote_host
                and self.transfer_client is not None):
            self._pending_pulls[rid] = (ktp.remote_host, ktp.remote_port,
                                        ktp.remote_request_id)
            try:
                n, outcome, released = pull_prefix_into(self, ktp, token_ids,
                                                        lora_id, mm_hashes)
            except Exception:
                n, outcome, released = 0, "error", False
            if released:
                self._pending_pulls.pop(rid, None)
        durable = getattr(self.engine, "durable", None)
        if n == 0 and durable is not None and ktp.block_hashes:
            # durable-tier rung: the peer died/missed, or the router stamped
            # the durable tier directly — the cluster store outlives replicas
            dn, d_outcome = self._durable_get(ktp.block_hashes, token_ids,
                                              lora_id, mm_hashes)
            if dn or tier == "durable":
                n, outcome, tier = dn, d_outcome, "durable"
                peer = f"{durable.cfg.host}:{durable.cfg.port}"
        pull_s = time.monotonic() - t0
        self.server_metrics.prefix_pull_seconds.labels(
            outcome=outcome).observe(pull_s)
        if n:
            self.transfer_stats["prefix_pull_blocks"] += n
        else:
            self.transfer_stats["pull_failures"] += 1
        # the pull runs before admission opens the flight record; start() is
        # idempotent, so open it here and let add_request backfill the model
        self.engine.flight.start(rid)
        # durable fetches stay on the kv_pull event NAME — attribution keys
        # on names (obs/attribution.py), so PR-13 sum-to-wall is untouched;
        # `tier` is the distinction dashboards and ledger tests filter on
        self.engine.flight.record(rid, "kv_pull", outcome=outcome, blocks=n,
                                  ms=round(pull_s * 1e3, 3), tier=tier,
                                  peer=peer)
        return n

    def _durable_get(self, block_hashes, token_ids, lora_id=None,
                     mm_hashes: list = ()) -> tuple[int, str]:
        """Durable-tier rung: fetch the verified consecutive prefix from the
        cluster store and inject it exactly like a peer pull — hash-chain
        verified against THIS prompt, shape-checked, committed as ordinary
        prefix-cache entries. Returns (blocks_injected, kv_pull outcome)."""
        from llmd_tpu.disagg.transfer import PulledKV, inject_into_engine

        durable = self.engine.durable
        t0 = time.monotonic()
        want = [int(h) for h in block_hashes]
        n, blocks, fetch_outcome = durable.get(want)
        injected = 0
        if n and blocks is not None:
            pulled = PulledKV(block_hashes=want[:n],
                              token_chunks=[[] for _ in range(n)],
                              blocks=blocks)
            try:
                injected = self.async_engine.run_locked(
                    lambda: inject_into_engine(self.engine, pulled, token_ids,
                                               lora_id, list(mm_hashes)))
            except ValueError:
                # block-shape / chain mismatch: the verifier rejected the
                # payload — fall down the ladder, never commit suspect bytes
                injected, fetch_outcome = 0, "corrupt"
            except Exception:
                injected, fetch_outcome = 0, "error"
            if injected:
                self.transfer_stats["injected_blocks"] += injected
        self.engine.flight.record_system(
            "kv_durable_get", outcome=fetch_outcome, blocks=injected,
            ms=round((time.monotonic() - t0) * 1e3, 3))
        self.server_metrics.kv_durable_get.labels(
            outcome=fetch_outcome).inc()
        if injected:
            return injected, "hit"
        if fetch_outcome in ("ok", "miss", "breaker_open"):
            return 0, "miss"
        return 0, "error"

    def _flush_for_drain(self, budget_s: float) -> tuple[int, int]:
        """Final write-back before retirement: stage the resident prefix
        working set under the engine lock (cheap device slicing), drain the
        host bytes off-lock, enqueue, then synchronously empty the flush
        queue under the remaining budget. A hung store costs at most the
        budget — the remainder is abandoned, and drain still retires."""
        from llmd_tpu.disagg.transfer import drain_staged
        from llmd_tpu.kv.writeback import stage_resident_blocks

        t0 = time.monotonic()
        wb = self.engine.writeback
        try:
            hashes, parts = self.async_engine.run_locked(
                lambda: stage_resident_blocks(self.engine, wb.max_blocks))
            if hashes:
                wb.offer(hashes, drain_staged(parts))
        except Exception:
            pass  # flush is best-effort; drain must still retire on time
        remaining = max(0.0, budget_s - (time.monotonic() - t0))
        return wb.flush_for_drain(remaining)

    def _release_pending_pull(self, rid: str) -> None:
        """Free the peer-side registration for a retired/aborted request
        (satellite fix: a dead puller must not pin peer exports until TTL)."""
        pending = self._pending_pulls.pop(rid, None)
        if pending is None or self.transfer_client is None:
            return
        host, port, remote_rid = pending
        try:
            if self.transfer_client.notify(host, port, remote_rid):
                self.transfer_stats["released"] += 1
        except Exception:
            pass  # peer gone; its TTL reaper cleans up

    def _tokenize_body(self, body: dict) -> list[int]:
        if body.get("prompt_token_ids"):
            return list(body["prompt_token_ids"])
        if "messages" in body:
            text = flatten_messages(body["messages"])
        else:
            text = str(body.get("prompt", ""))
        return self.tokenizer.encode(text)

    def _mm_token_stream(self, body: dict) -> tuple[list[int], list[dict]]:
        """VL token stream: media parts expand to cfg.mm_tokens placeholder ids.

        Shared by /render and the generate path — the router's precise
        token-producer tokenizes via /render, so the engine MUST hash blocks
        over this exact stream or prefix-cache routing silently scores 0 for
        every multimodal request. Returns (tokens, media parts in order)."""
        from llmd_tpu.disagg.encode import is_media_part

        cfg = self.engine.model_cfg
        pieces: list = []  # str segments; None marks a media slot
        parts: list[dict] = []
        for m in body.get("messages", []) or []:
            content = m.get("content", "")
            pieces.append(f"{m.get('role', '')}: ")
            if isinstance(content, list):
                for part in content:
                    if is_media_part(part):
                        pieces.append(None)
                        parts.append(part)
                    elif isinstance(part, dict):
                        pieces.append(part.get("text", "") + " ")
                    else:
                        pieces.append(str(part) + " ")
            else:
                pieces.append(str(content))
            pieces.append("\n")
        token_ids: list[int] = []
        for p in pieces:
            if p is None:
                token_ids.extend([cfg.mm_placeholder_id] * cfg.mm_tokens)
            elif p:
                token_ids.extend(self.tokenizer.encode(p))
        return token_ids, parts

    def _tokenize_mm(self, body: dict) -> tuple[list[int], Optional[list]]:
        """VL tokenization + embedding resolution: E-stage wire items match by
        canonical part hash; missing items encode in-process when this server
        has a vision tower, otherwise the request degrades to the text-only
        flatten rendering (encode pool down ≠ failed request).

        Returns (tokens, mm_items) — mm_items None means degraded text-only."""
        from llmd_tpu.disagg.encode import (
            VisionRunner,
            media_bytes_from_part,
            mm_item_from_wire,
            part_identity,
        )

        cfg = self.engine.model_cfg
        token_ids, parts = self._mm_token_stream(body)
        wire_by_hash: dict[bytes, tuple[bytes, "object"]] = {}
        for d in body.get("mm_items") or []:
            try:
                h, emb = mm_item_from_wire(d, cfg.hidden_size)
                wire_by_hash[h] = (h, emb)
            except Exception:
                continue  # malformed wire item: treat as missing
        mm_items = []
        missing: list[tuple[int, dict]] = []
        for i, part in enumerate(parts):
            h = part_identity(part)
            got = wire_by_hash.get(h)
            if got is not None:
                mm_items.append(got)
            else:
                mm_items.append(None)
                missing.append((i, part))
        if missing:
            if not cfg.has_vision:
                # true E/PD worker without a tower: degrade to text-only
                # (media identity still lands in the stream via flatten's
                # <kind:hash> rendering) rather than 500ing the request
                return self._tokenize_body(body), None
            with self._vision_lock:
                if self._vision is None:
                    self._vision = VisionRunner(cfg)
            payloads = [media_bytes_from_part(part) or b"" for _, part in missing]
            encoded = self._vision.encode(payloads)
            for (i, part), (_h, emb) in zip(missing, encoded):
                mm_items[i] = (part_identity(part), emb)
        return token_ids, mm_items

    # -- handlers ----------------------------------------------------------
    def _admission_block(self, request: web.Request) -> Optional[web.Response]:
        """Shared admission gate: draining → 503 (retryable, so the router
        re-schedules the request on another endpoint); an already-expired
        forwarded deadline (x-request-timeout remainder ≤ 0) → 504 before any
        tokenization or engine work is spent on it."""
        if self._draining:
            return web.json_response({"error": {"message": "draining"}},
                                     status=503, headers={"Retry-After": "1"})
        raw = request.headers.get(HDR_REQUEST_TIMEOUT)
        if raw is not None:
            try:
                budget = float(raw)
            except ValueError:
                return None  # malformed header: ignore, don't reject
            if budget <= 0:
                return web.json_response(
                    {"error": {"message": "deadline exceeded"}}, status=504)
        return None

    async def _completions(self, request: web.Request):
        return await self._generate(request, chat=False)

    async def _chat(self, request: web.Request):
        return await self._generate(request, chat=True)

    async def _generate(self, request: web.Request, chat: bool):
        blocked = self._admission_block(request)
        if blocked is not None:
            return blocked
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        try:
            # malformed structured specs (bad schema/regex/logit_bias) fail as
            # 400 here, before the request counts or touches the engine
            validate_structured_body(body)
        except ValueError as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        self.request_count += 1
        mm_items = None
        if self.engine.model_cfg.mm_tokens > 0 and _body_has_media(body):
            try:
                # executor thread: in-process vision encode (jit compile +
                # device compute in combined-PD mode) must not stall the loop
                token_ids, mm_items = await asyncio.get_running_loop().run_in_executor(
                    None, self._tokenize_mm, body)
            except Exception as e:
                return web.json_response(
                    {"error": {"message": f"multimodal content: {e}"}}, status=400)
        else:
            token_ids = self._tokenize_body(body)
        mm_hashes = [h for h, _ in mm_items] if mm_items else []
        sampling = _sampling_from_body(body)
        if not sampling.ignore_eos:
            sampling.stop_token_ids = tuple(sampling.stop_token_ids) + (self.tokenizer.eos_id,)
        rid = f"cmpl-{uuid.uuid4().hex[:16]}"
        stream = bool(body.get("stream", False))
        created = int(time.time())
        model = body.get("model", self.model_name)
        lora_id = body.get("lora_adapter")
        # vLLM semantics: requesting a loaded adapter's name as the model routes
        # to that adapter (adapter-rollout.md canary flow relies on this)
        reg = self.engine.lora_registry
        if lora_id is None and reg is not None and reg.has(model):
            lora_id = model
        if lora_id is not None and (reg is None or not reg.has(lora_id)):
            # vLLM 404 semantics — covers unknown adapters AND LoRA serving being
            # disabled (silently answering with base weights would mislead the
            # client and poison the prefix cache under the adapter's name)
            return web.json_response(
                {"error": {"message": f"unknown LoRA adapter {lora_id!r}"}}, status=404)

        from llmd_tpu.obs.tracing import extract_traceparent

        span = self.tracer.start_span(
            "engine.generate", parent=extract_traceparent(dict(request.headers)),
            **{"llm_d.model": model, "llm_d.prompt_tokens": len(token_ids),
               "llm_d.stream": stream})

        ktp = KVTransferParams.from_dict(body.get("kv_transfer_params"))
        if ktp.do_remote_prefill and self.transfer_client is not None:
            span.add_event("kv_transfer.pull")
            await asyncio.get_running_loop().run_in_executor(
                None, self._pull_remote_kv, ktp, token_ids, lora_id, mm_hashes,
                rid
            )
        elif (ktp.do_prefix_pull and ktp.block_hashes
              and (self.transfer_client is not None
                   or getattr(self.engine, "durable", None) is not None)):
            # KV plane: the router found this prefix cached on a peer or in
            # the durable store — pull it before admission; failure falls
            # through to the offload tier and then plain re-prefill
            span.add_event("kv_plane.pull")
            await asyncio.get_running_loop().run_in_executor(
                None, self._pull_prefix_kv, rid, ktp, token_ids, lora_id,
                mm_hashes
            )

        # the engine mints its own rid, so the router's tenant header is the
        # only identity link: open (or backfill) the flight record with it
        # before admission so the engine-side ledger carries the tenant too
        from llmd_tpu.core.request import HDR_TENANT, clamp_tenant

        self.engine.flight.start(
            rid, tenant=clamp_tenant(request.headers.get(HDR_TENANT)))

        try:
            gen = self.async_engine.generate(rid, token_ids, sampling, lora_id,
                                             rank=self.rank, mm_items=mm_items,
                                             trace_ctx=span.context)
            if not stream:
                out_ids: list[int] = []
                cached = 0
                reason = None
                async for out in gen:
                    out_ids.extend(out.new_token_ids)
                    cached = out.num_cached_prompt_tokens
                    reason = out.finish_reason
                text = self.tokenizer.decode(out_ids)
                usage = {
                    "prompt_tokens": len(token_ids), "completion_tokens": len(out_ids),
                    "total_tokens": len(token_ids) + len(out_ids), "cached_tokens": cached,
                }
                choice = (
                    {"index": 0, "message": {"role": "assistant", "content": text},
                     "finish_reason": reason}
                    if chat else
                    {"index": 0, "text": text, "finish_reason": reason}
                )
                payload = {
                    "id": rid, "object": "chat.completion" if chat else "text_completion",
                    "created": created, "model": model, "usage": usage, "choices": [choice],
                }
                if ktp.do_remote_decode and self.transfer_source is not None:
                    # two-phase staging: the engine lock is held only long enough
                    # to dispatch the chunked gathers (+ async D2H copies); the
                    # byte drain + registration runs in an executor thread while
                    # the engine keeps stepping other requests
                    def _begin():
                        return self.async_engine.run_locked(
                            lambda: export_begin(
                                self.engine, rid, token_ids, lora_id,
                                staging_pages=self.engine.cfg.offload_staging_blocks,
                                mm_hashes=mm_hashes,
                            )
                        )

                    loop = asyncio.get_running_loop()
                    out_params, staged = await loop.run_in_executor(None, _begin)
                    if staged is not None:
                        await loop.run_in_executor(
                            None, lambda: export_finish(staged, self.transfer_source)
                        )
                    # advertise a routable host, never the bind-any address — the
                    # sidecar falls back to the prefiller's header host when unset
                    routable = self.advertise_host or self.host
                    if routable not in ("0.0.0.0", "::", ""):
                        out_params.remote_host = routable
                    out_params.remote_port = self.transfer_source.port
                    payload["kv_transfer_params"] = out_params.to_dict()
                span.set_attribute("llm_d.completion_tokens", len(out_ids))
                span.set_attribute("llm_d.cached_tokens", cached)
                span.end()
                return web.json_response(payload)

            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream", "Cache-Control": "no-cache",
            })
            await resp.prepare(request)
            n_out = 0
            async for out in gen:
                piece = self.tokenizer.decode(out.new_token_ids)
                n_out += len(out.new_token_ids)
                chunk = {
                    "id": rid, "created": created, "model": model,
                    "object": "chat.completion.chunk" if chat else "text_completion",
                    "choices": [
                        {"index": 0, "delta": {"content": piece},
                         "finish_reason": out.finish_reason if out.finished else None}
                        if chat else
                        {"index": 0, "text": piece,
                         "finish_reason": out.finish_reason if out.finished else None}
                    ],
                }
                if out.finished:
                    chunk["usage"] = {
                        "prompt_tokens": len(token_ids), "completion_tokens": n_out,
                        "total_tokens": len(token_ids) + n_out,
                        "cached_tokens": out.num_cached_prompt_tokens,
                    }
                await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            span.set_attribute("llm_d.completion_tokens", n_out)
            span.end()
            return resp
        except ValueError as e:
            span.set_error(str(e))
            return web.json_response({"error": {"message": str(e)}}, status=400)
        finally:
            if rid in self._pending_pulls:
                # retire/abort/disconnect with the peer registration still
                # live (pull died between serve and notify): release it now.
                # Not awaited — this finally also runs under task cancellation
                # (client disconnect), where any await would re-raise.
                asyncio.get_running_loop().run_in_executor(
                    None, self._release_pending_pull, rid)
            span.end()  # idempotent backstop

    async def _embeddings(self, request: web.Request):
        """OpenAI /v1/embeddings: mean-pooled L2-normalised final hidden states
        (openai-parser endpoint list, request-handling.md:50-73)."""
        blocked = self._admission_block(request)
        if blocked is not None:
            return blocked
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        inp = body.get("input")
        if inp is None:
            return web.json_response({"error": {"message": "input required"}}, status=400)
        items = [inp] if isinstance(inp, (str,)) else list(inp)
        if items and isinstance(items[0], int):  # single pre-tokenized prompt
            items = [items]
        model = body.get("model", self.model_name)
        lora_id = body.get("lora_adapter")
        reg = self.engine.lora_registry
        if lora_id is None and reg is not None and reg.has(model):
            lora_id = model
        if lora_id is not None and (reg is None or not reg.has(lora_id)):
            return web.json_response(
                {"error": {"message": f"unknown LoRA adapter {lora_id!r}"}}, status=404)

        loop = asyncio.get_running_loop()
        data = []
        total_tokens = 0
        for i, item in enumerate(items):
            ids = item if isinstance(item, list) else self.tokenizer.encode(str(item))
            if not ids:
                return web.json_response(
                    {"error": {"message": f"empty input at index {i}"}}, status=400)
            total_tokens += len(ids)
            try:
                vec = await loop.run_in_executor(
                    None,
                    lambda ids=ids: self.async_engine.run_locked(
                        lambda: self.engine.embed(ids, lora_id, rank=self.rank)))
            except RuntimeError as exc:
                return web.json_response({"error": {"message": str(exc)}}, status=503)
            data.append({"object": "embedding", "index": i, "embedding": vec})
        self.request_count += 1
        return web.json_response({
            "object": "list", "model": model, "data": data,
            "usage": {"prompt_tokens": total_tokens, "total_tokens": total_tokens},
        })

    # -- Responses / Conversations APIs ------------------------------------
    # The conversation store is engine-local (a pod-resident dict, like vLLM's);
    # the router keeps conversation traffic sticky by id so follow-ups land on
    # the pod holding the state AND its KV prefix cache.

    @staticmethod
    def _responses_input_to_messages(inp) -> list[dict]:
        if isinstance(inp, str):
            return [{"role": "user", "content": inp}]
        out = []
        for item in inp or []:
            if isinstance(item, dict):
                out.append({"role": item.get("role", "user"),
                            "content": item.get("content", "")})
        return out

    async def _responses(self, request: web.Request):
        """OpenAI Responses API (epp-http-apis.md:153-183): ``input`` + optional
        ``conversation`` id; conversation context prepends, and the exchange is
        appended back to the store."""
        blocked = self._admission_block(request)
        if blocked is not None:
            return blocked
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        conv_id = body.get("conversation")
        conv = self._conversations.get(conv_id) if conv_id else None
        if conv_id and conv is None:
            return web.json_response(
                {"error": {"message": f"unknown conversation {conv_id!r}"}}, status=404)
        new_msgs = self._responses_input_to_messages(body.get("input", ""))
        messages = (list(conv["items"]) if conv else []) + new_msgs
        max_out = int(body.get("max_output_tokens", body.get("max_tokens", 16)))
        chat_body = {
            "model": body.get("model", self.model_name),
            "messages": messages,
            "max_tokens": max_out,
            "temperature": body.get("temperature", 1.0),
        }
        if body.get("ignore_eos"):
            chat_body["ignore_eos"] = True
        # structured-output fields ride through to the shared sampling parse
        for key in ("response_format", "guided_choice", "guided_regex",
                    "logit_bias"):
            if body.get(key) is not None:
                chat_body[key] = body[key]
        try:
            validate_structured_body(chat_body)
        except ValueError as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        # same tokenization path as chat (VL content parts included)
        mm_items = None
        if self.engine.model_cfg.mm_tokens > 0 and _body_has_media(chat_body):
            try:
                token_ids, mm_items = await asyncio.get_running_loop().run_in_executor(
                    None, self._tokenize_mm, chat_body)
            except Exception as e:
                return web.json_response(
                    {"error": {"message": f"multimodal content: {e}"}}, status=400)
        else:
            token_ids = self._tokenize_body(chat_body)
        sampling = _sampling_from_body(chat_body)
        if not sampling.ignore_eos:
            sampling.stop_token_ids = tuple(sampling.stop_token_ids) + (self.tokenizer.eos_id,)
        rid = f"resp-{uuid.uuid4().hex[:16]}"
        out_ids: list[int] = []
        finish = None
        try:
            async for out in self.async_engine.generate(rid, token_ids, sampling,
                                                        rank=self.rank,
                                                        mm_items=mm_items):
                out_ids.extend(out.new_token_ids)
                finish = out.finish_reason
        except ValueError as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        text = self.tokenizer.decode(out_ids)
        usage = {"prompt_tokens": len(token_ids), "completion_tokens": len(out_ids),
                 "total_tokens": len(token_ids) + len(out_ids)}
        inner = {"model": chat_body["model"]}
        status = "completed" if finish in (None, "stop", "eos") else "incomplete"
        resp = {
            "id": f"resp_{uuid.uuid4().hex[:12]}",
            "object": "response",
            "created_at": int(time.time()),
            "model": inner["model"],
            "status": status,
            "output": [{
                "id": f"msg_{uuid.uuid4().hex[:12]}",
                "type": "message", "role": "assistant", "status": "completed",
                "content": [{"type": "output_text", "text": text, "annotations": []}],
            }],
            "max_output_tokens": max_out,
            "usage": {"input_tokens": usage["prompt_tokens"],
                      "output_tokens": usage["completion_tokens"],
                      "total_tokens": usage["total_tokens"]},
        }
        if status == "incomplete":
            resp["incomplete_details"] = {"reason": "max_output_tokens"}
        if conv is not None:
            conv["items"].extend(new_msgs)
            conv["items"].append({"role": "assistant", "content": text})
            self._conv_trim(conv)
        if conv_id:
            resp["conversation"] = conv_id
        return web.json_response(resp)

    def _conv_trim(self, conv: dict) -> None:
        if len(conv["items"]) > self._max_conv_items:
            del conv["items"][: len(conv["items"]) - self._max_conv_items]

    async def _conv_create(self, request: web.Request):
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        # routers inject a pre-generated id so hash-of-id sticky routing is
        # deterministic across EPP replicas; direct clients get a fresh one
        cid = str(body.get("id") or f"conv_{uuid.uuid4().hex[:12]}")
        conv = {"id": cid, "object": "conversation", "created_at": int(time.time()),
                "items": list(body.get("items", []) or []),
                "metadata": body.get("metadata") or {}}
        self._conv_trim(conv)
        self._conversations[cid] = conv
        while len(self._conversations) > self._max_conversations:
            self._conversations.popitem(last=False)
        return web.json_response({k: v for k, v in conv.items() if k != "items"})

    def _conv_or_404(self, request):
        conv = self._conversations.get(request.match_info["cid"])
        if conv is not None:
            self._conversations.move_to_end(request.match_info["cid"])
        return conv

    async def _conv_get(self, request: web.Request):
        conv = self._conv_or_404(request)
        if conv is None:
            return web.json_response({"error": {"message": "not found"}}, status=404)
        return web.json_response({k: v for k, v in conv.items() if k != "items"})

    async def _conv_delete(self, request: web.Request):
        conv = self._conversations.pop(request.match_info["cid"], None)
        if conv is None:
            return web.json_response({"error": {"message": "not found"}}, status=404)
        return web.json_response({"id": conv["id"], "object": "conversation.deleted",
                                  "deleted": True})

    async def _conv_add_items(self, request: web.Request):
        conv = self._conv_or_404(request)
        if conv is None:
            return web.json_response({"error": {"message": "not found"}}, status=404)
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        items = body.get("items", [])
        conv["items"].extend(items)
        self._conv_trim(conv)
        return web.json_response({"object": "list", "data": items})

    async def _conv_list_items(self, request: web.Request):
        conv = self._conv_or_404(request)
        if conv is None:
            return web.json_response({"error": {"message": "not found"}}, status=404)
        return web.json_response({"object": "list", "data": conv["items"]})

    async def _render(self, request: web.Request):
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}}, status=400)
        if self.engine.model_cfg.mm_tokens > 0 and _body_has_media(body):
            # router-visible rendering must match generate-path hashing exactly
            token_ids, _ = self._mm_token_stream(body)
            return web.json_response({"prompt_token_ids": token_ids})
        return web.json_response({"prompt_token_ids": self._tokenize_body(body)})

    async def _metrics(self, request: web.Request):
        # Gauges mirror engine.stats at scrape time; counters/histograms are
        # incremented live inside the step loop. The whole exposition renders
        # through Registry.expose() — the one code path shared with the
        # router — so label values (LoRA adapter names especially) are always
        # escaped per the text format spec.
        em = self.engine.metrics
        s = self.engine.stats
        em.requests_waiting.set(s.num_waiting)
        em.requests_running.set(s.num_running)
        em.kv_usage.set(s.kv_utilization)
        # counters the step loop doesn't own (recompute path) stay derived
        # from stats via the registry increments at their emit sites; the
        # lora info gauge is rebuilt each scrape (its labels ARE the data)
        if self.engine.lora_registry is not None:
            info = self.engine.lora_registry.metrics_info()
            em.lora_info.clear()
            em.lora_info.labels(
                max_lora=info["max_lora"],
                running_lora_adapters=info["running_lora_adapters"],
                waiting_lora_adapters=info["waiting_lora_adapters"],
            ).set(1)
        return web.Response(
            text=self.engine.registry.expose() + self.registry.expose())

    async def _health(self, request: web.Request):
        if self._draining:
            # 503 = readiness-probe semantics: load balancers drop us from
            # rotation while the in-flight tail finishes
            return web.json_response(
                {"status": "draining", "inflight": len(self.engine.seqs)},
                status=503)
        mon = getattr(self.engine, "monitor", None)
        reason = mon.unhealthy_reason() if mon is not None else None
        if reason is not None:
            # device fault (stalled step loop / dead fabric): same 503
            # readiness semantics — the PoolController sweep retires us and
            # the router's breakers route around us; the structured reason
            # rides along so the retirement event says WHY
            return web.json_response(
                {"status": "unhealthy", **reason}, status=503)
        return web.json_response({"status": "ok"})

    async def _drain(self, request: web.Request):
        """POST /drain[?timeout_s=30] — stop admissions, wait for in-flight
        requests to finish (bounded), report the result. ``{"enable": false}``
        in the body re-opens admissions (rollback of an aborted drain)."""
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if body.get("enable") is False:
            self._draining = False
            return web.json_response({"status": "ok", "draining": False})
        try:
            timeout_s = float(request.query.get("timeout_s", 30.0))
        except ValueError:
            return web.json_response(
                {"error": {"message": "timeout_s must be a number"}}, status=400)
        t0 = time.monotonic()
        if not self._draining:
            self._draining = True
            self.engine.flight.record_system(
                "drain_start", inflight=len(self.engine.seqs))
        while self.engine.seqs and time.monotonic() - t0 < timeout_s:
            await asyncio.sleep(0.02)
        drained = not self.engine.seqs
        flush_info = {}
        if drained and getattr(self.engine, "writeback", None) is not None:
            # write the resident working set back to the durable store before
            # retirement, capped by min(drain budget, remaining drain window)
            # so a hung store cannot push retirement past the pool's timeout
            budget = min(self.engine.durable.cfg.drain_budget_s,
                         max(0.0, timeout_s - (time.monotonic() - t0)))
            flushed, abandoned = await asyncio.get_running_loop(
                ).run_in_executor(None, self._flush_for_drain, budget)
            flush_info = {"flushed_blocks": flushed,
                          "abandoned_blocks": abandoned}
        self.engine.flight.record_system(
            "drain_done", drained=drained, inflight=len(self.engine.seqs),
            waited_ms=round((time.monotonic() - t0) * 1e3, 1), **flush_info)
        return web.json_response(
            {"status": "drained" if drained else "timeout",
             "inflight": len(self.engine.seqs)},
            status=200 if drained else 504)

    async def _debug_requests(self, request: web.Request):
        from llmd_tpu.obs.events import debug_list_response

        status, payload = debug_list_response(
            self.engine.flight, request.rel_url.query)
        return web.json_response(payload, status=status)

    async def _debug_request(self, request: web.Request):
        from llmd_tpu.obs.events import debug_detail_response

        status, payload = debug_detail_response(
            self.engine.flight, request.match_info["rid"])
        return web.json_response(payload, status=status)

    async def _debug_profile(self, request: web.Request):
        """GET /debug/profile?seconds=N — capture one jax.profiler window
        into LLMD_PROFILE_DIR and describe the artifact. One at a time (409
        while busy); the capture blocks in an executor, not on the loop."""
        from llmd_tpu.obs.device import ProfileBusy

        mon = getattr(self.engine, "monitor", None)
        if mon is None:
            return web.json_response(
                {"error": {"message": "device monitor not running"}},
                status=503)
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.json_response(
                {"error": {"message": "seconds must be numeric"}}, status=400)
        try:
            result = await asyncio.get_running_loop().run_in_executor(
                None, mon.capture_profile, seconds)
        except ProfileBusy as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=409)
        except Exception as e:
            return web.json_response(
                {"error": {"message": f"profile capture failed: {e}"}},
                status=500)
        return web.json_response(result)

    async def _models(self, request: web.Request):
        data = [{"id": self.model_name, "object": "model"}]
        if self.engine.lora_registry is not None:  # adapters list as models (vLLM)
            data += [{"id": name, "object": "model", "parent": self.model_name}
                     for name in sorted(self.engine.lora_registry.slots)]
        return web.json_response({"object": "list", "data": data})

    async def _load_lora(self, request: web.Request):
        """POST /v1/load_lora_adapter {lora_name, lora_path?} (vLLM runtime-LoRA
        API; VLLM_ALLOW_RUNTIME_LORA_UPDATING equivalent is always-on here)."""
        if self.engine.lora_registry is None:
            return web.json_response(
                {"error": "LoRA serving disabled (EngineConfig.lora unset)"}, status=400)
        try:
            body = await request.json()
            name = body["lora_name"]
        except Exception:
            return web.json_response({"error": "lora_name required"}, status=400)
        import re

        if not isinstance(name, str) or not re.fullmatch(r"[A-Za-z0-9._/\-]{1,128}", name):
            # names land in Prometheus label values and hash keys — an unescaped
            # quote would corrupt the whole /metrics exposition
            return web.json_response({"error": "invalid lora_name"}, status=400)
        path = body.get("lora_path")

        def _load_and_install() -> int:
            weights = None
            if path:  # filesystem resolver: npz with lora_{A,B}_{target} arrays
                import numpy as _np

                with _np.load(path) as z:  # in executor: big files must not
                    weights = {k: z[k] for k in z.files}  # block the event loop
            return self.async_engine.run_locked(
                lambda: self.engine.load_lora_adapter(name, weights))

        try:
            slot = await asyncio.get_running_loop().run_in_executor(
                None, _load_and_install)
        except RuntimeError as exc:
            return web.json_response({"error": str(exc)}, status=409)
        except Exception as exc:
            return web.json_response(
                {"error": f"cannot load adapter: {exc}"}, status=400)
        return web.json_response({"status": "ok", "lora_name": name, "slot": slot})

    async def _unload_lora(self, request: web.Request):
        if self.engine.lora_registry is None:
            return web.json_response(
                {"error": "LoRA serving disabled (EngineConfig.lora unset)"}, status=400)
        try:
            body = await request.json()
            name = body["lora_name"]
        except Exception:
            return web.json_response({"error": "lora_name required"}, status=400)
        try:
            ok = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self.async_engine.run_locked(
                    lambda: self.engine.unload_lora_adapter(name)))
        except RuntimeError as exc:  # in-flight requests hold the adapter
            return web.json_response({"error": str(exc)}, status=409)
        if not ok:
            return web.json_response({"error": f"unknown adapter {name!r}"}, status=404)
        return web.json_response({"status": "ok", "lora_name": name})
