"""Async facade over LLMEngine: a dedicated step-loop thread feeding asyncio streams.

JAX dispatch blocks the calling thread, so the engine loop lives off the event loop;
request submission and token delivery cross the boundary through thread-safe queues —
the same split the reference's engines use (API server process ↔ engine core).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import AsyncIterator, Optional

from llmd_tpu.core.request import SamplingParams
from llmd_tpu.engine.engine import EngineOutput, LLMEngine


class AsyncLLMEngine:
    def __init__(self, engine: LLMEngine, idle_sleep_s: float = 0.002) -> None:
        self.engine = engine
        self._idle_sleep = idle_sleep_s
        self._lock = threading.Lock()
        # request_id -> (caller loop, stream queue); written from caller
        # event loops, drained/popped from the engine thread.
        # guarded-by: _lock
        self._streams: dict[str, tuple[asyncio.AbstractEventLoop, asyncio.Queue]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # shared across rank frontends — only one step loop
        self._thread = threading.Thread(target=self._run, name="engine-loop", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.is_set():
            # heartbeat BEFORE taking the lock: a step wedged on the device
            # holds the lock, so stamping inside it would mask the stall the
            # watchdog (obs/device.py) exists to catch
            mon = getattr(self.engine, "monitor", None)
            if mon is not None:
                mon.heartbeat()
            with self._lock:
                has_work = self.engine.has_work()
                outputs = self.engine.step() if has_work else []
            for out in outputs:
                with self._lock:
                    entry = self._streams.get(out.request_id)
                    if out.finished:
                        self._streams.pop(out.request_id, None)
                if entry is None:
                    continue
                loop, q = entry
                loop.call_soon_threadsafe(q.put_nowait, out)
            if not has_work:
                time.sleep(self._idle_sleep)

    # -- API ---------------------------------------------------------------
    async def generate(
        self,
        request_id: str,
        token_ids: list[int],
        sampling: SamplingParams,
        lora_id: Optional[str] = None,
        rank: int = 0,
        mm_items=None,
        trace_ctx=None,
    ) -> AsyncIterator[EngineOutput]:
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        try:
            with self._lock:  # stream registration + admission are atomic
                self._streams[request_id] = (loop, q)
                self.engine.add_request(request_id, token_ids, sampling, lora_id,
                                        rank=rank, mm_items=mm_items,
                                        trace_ctx=trace_ctx)
        except ValueError:
            with self._lock:
                self._streams.pop(request_id, None)
            raise
        try:
            while True:
                out: EngineOutput = await q.get()
                yield out
                if out.finished:
                    return
        finally:
            with self._lock:
                self._streams.pop(request_id, None)
            if request_id in self.engine.seqs:
                with self._lock:
                    self.engine.abort(request_id)

    def stats(self):
        return self.engine.stats

    def run_locked(self, fn):
        """Run fn() while the step loop is paused — for callers that must mutate
        engine state (KV injection/export) without racing a step in flight."""
        with self._lock:
            return fn()
